"""BERT family (reference ecosystem: PaddleNLP's bert modeling, the
second pillar model family next to GPT; architecture: Devlin et al.,
post-LN encoder).

TPU-native: pure functional blocks over jnp with the repo's Layer system;
attention routes through nn.functional.scaled_dot_product_attention (the
Pallas flash kernel on TPU; fp32-softmax reference path with additive
masks).  Architectural EXACTNESS is oracle-tested
against a weight-mapped `transformers.BertModel` (tests/test_bert.py) —
the strongest parity check available in this image.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.container import LayerList
from ..nn.layers.norm import LayerNorm

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM",
           "BertForSequenceClassification", "bert_tiny"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


class _BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = jnp.arange(s)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(pos) + \
            self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class _BertSelfAttention(Layer):
    """Hand-rolled q/k/v/out projections (rather than nn.MultiHeadAttention)
    so parameter names map one-to-one onto HF/PaddleNLP BERT checkpoints —
    the weight-mapped parity oracle depends on that naming.  The attention
    MATH routes through the shared F.scaled_dot_product_attention (flash
    kernel on TPU, fp32-softmax reference path otherwise)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.cfg = cfg
        self.query = Linear(h, h)
        self.key = Linear(h, h)
        self.value = Linear(h, h)
        self.out = Linear(h, h)

    def forward(self, x, attn_mask=None):
        cfg = self.cfg
        b, s, h = x.shape
        nh, hd = cfg.num_attention_heads, cfg.head_dim

        def split(t):
            return t.reshape(b, s, nh, hd)

        q, k, v = split(self.query(x)), split(self.key(x)), split(self.value(x))
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=cfg.attention_probs_dropout_prob,
            is_causal=False, training=self.training)
        return self.out(ctx.reshape(b, s, h))


class _BertLayer(Layer):
    """Post-LN block (BERT): x = LN(x + attn(x)); x = LN(x + ffn(x))."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = _BertSelfAttention(cfg)
        self.attn_norm = LayerNorm(cfg.hidden_size,
                                   epsilon=cfg.layer_norm_eps)
        self.intermediate = Linear(cfg.hidden_size, cfg.intermediate_size)
        self.output = Linear(cfg.intermediate_size, cfg.hidden_size)
        self.ffn_norm = LayerNorm(cfg.hidden_size,
                                  epsilon=cfg.layer_norm_eps)
        self.drop = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.attn_norm(x + self.drop(self.attention(x, attn_mask)))
        ffn = self.output(F.gelu(self.intermediate(x), approximate=False))
        return self.ffn_norm(x + self.drop(ffn))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = _BertEmbeddings(cfg)
        self.encoder = LayerList([_BertLayer(cfg)
                                  for _ in range(cfg.num_hidden_layers)])
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        """Returns (sequence_output [b,s,h], pooled_output [b,h]).
        ``attention_mask``: [b, s] with 1 = attend (reference contract);
        converted to the additive -inf form internally."""
        add_mask = None
        if attention_mask is not None:
            m = jnp.asarray(attention_mask, jnp.float32)
            add_mask = (1.0 - m)[:, None, None, :] * -1e9
        x = self.embeddings(input_ids, token_type_ids)
        for blk in self.encoder:
            x = blk(x, add_mask)
        pooled = jnp.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForMaskedLM(Layer):
    """MLM head tied to the word embedding table (BERT convention)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = LayerNorm(cfg.hidden_size,
                                        epsilon=cfg.layer_norm_eps)
        self.decoder_bias = self.create_parameter(
            (cfg.vocab_size,), is_bias=True)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_norm(F.gelu(self.transform(seq),
                                       approximate=False))
        table = self.bert.embeddings.word_embeddings.weight
        return jnp.einsum("bsh,vh->bsv", h, table) + self.decoder_bias

    def loss(self, input_ids, labels, ignore_index: int = -100, **kw):
        logits = self(input_ids, **kw)
        return F.cross_entropy(logits.reshape(-1, self.cfg.vocab_size),
                               jnp.asarray(labels).reshape(-1),
                               ignore_index=ignore_index)


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2,
                 dropout: Optional[float] = None):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = Dropout(cfg.hidden_dropout_prob
                               if dropout is None else dropout)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))


def bert_tiny(**kw) -> BertConfig:
    return BertConfig(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=128,
                      max_position_embeddings=128,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0, **kw)
