"""GPT-MoE — expert-parallel decoder LM (BASELINE config #5).

Reference model surface: paddle.incubate.distributed.models.moe —
MoELayer-based GPT variants (the expert-parallel baseline config), gates
from gate/gshard_gate.py / switch_gate.py, dispatch via
global_scatter/global_gather (SURVEY.md §2.3 EP row).

TPU-native design: standard GPT blocks with every ``moe_every``-th FFN
replaced by distributed.moe.MoELayer; experts shard over the ``ep`` (or
given) mesh axis, dispatch einsums compile to all-to-all; the gates' aux
load-balance losses cross jit functionally as buffers and are summed into
the LM loss with ``aux_weight``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.container import LayerList
from ..nn.layers.norm import LayerNorm
from ..distributed.moe import MoELayer, ExpertFFN

__all__ = ["GPTMoEConfig", "GPTMoEForCausalLM", "gpt_moe_tiny"]


@dataclasses.dataclass
class GPTMoEConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_mult: int = 4
    num_experts: int = 8
    top_k: int = 2
    moe_every: int = 2            # every k-th block uses the MoE FFN
    gate: str = "gshard"          # naive | gshard | switch
    gate_kwargs: Optional[dict] = None   # extra gate args (e.g.
    # random_routing=False for deterministic gshard)
    # False | True (full jax.checkpoint) | a
    # jax.checkpoint_policies name (shared remat_wrap knob)
    remat: "bool | str" = False
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"
    # ParallelAxis / mesh-axis name for expert parallelism (EP)
    moe_group: Optional[object] = None
    # expert-internal tensor parallelism (reference: MoELayer(mp_group)):
    # True -> the canonical "mp" mesh axis; or a group/axis name
    mp_group: Optional[object] = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self):
        return self.hidden_size * self.ffn_mult


class _MoEBlock(Layer):
    def __init__(self, cfg: GPTMoEConfig, use_moe: bool):
        super().__init__()
        h = cfg.hidden_size
        self.cfg = cfg
        self.use_moe = use_moe
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.qkv = Linear(h, 3 * h)
        self.out_proj = Linear(h, h)
        self.ln_2 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        if use_moe:
            experts = [ExpertFFN(h, cfg.ffn_size)
                       for _ in range(cfg.num_experts)]
            gate_cfg = ({"type": cfg.gate, "topk": cfg.top_k}
                        if cfg.gate != "switch" else {"type": "switch"})
            gate_cfg.update(cfg.gate_kwargs or {})
            self.ffn = MoELayer(h, experts, gate=gate_cfg,
                                moe_group=cfg.moe_group,
                                mp_group=cfg.mp_group,
                                capacity_factor=cfg.capacity_factor)
        else:
            self.fc_in = Linear(h, cfg.ffn_size)
            self.fc_out = Linear(cfg.ffn_size, h)
        self.drop = Dropout(cfg.dropout)

    def _attn(self, x):
        cfg = self.cfg
        b, s, h = x.shape
        qkv = self.qkv(x).reshape(b, s, 3, cfg.num_heads, cfg.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=self.training)
        return self.out_proj(out.reshape(b, s, h))

    def forward(self, x):
        x = x + self.drop(self._attn(self.ln_1(x)))
        h = self.ln_2(x)
        if self.use_moe:
            m = self.ffn(h)
        else:
            m = self.fc_out(F.gelu(self.fc_in(h), approximate=True))
        return x + self.drop(m)


class GPTMoEForCausalLM(Layer):
    def __init__(self, cfg: GPTMoEConfig):
        super().__init__()
        self.cfg = cfg
        # GPT-2 init convention (std 0.02), matching the dense GPT's
        # VocabParallelEmbedding: the default N(0,1) embedding init with
        # the TIED head blows the logit scale to sqrt(h) at step 0 (the
        # r3 dryrun's MoE leg loss of 41 vs the dense leg's 5.6 was
        # exactly ln V + sigma^2/2 with sigma ~ 8)
        from ..nn.layer import ParamAttr
        from ..nn import initializer as I
        emb_init = lambda: ParamAttr(initializer=I.Normal(0.0, 0.02))
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size,
                             weight_attr=emb_init())
        self.wpe = Embedding(cfg.max_seq_len, cfg.hidden_size,
                             weight_attr=emb_init())
        self.h = LayerList([
            _MoEBlock(cfg, use_moe=(i % cfg.moe_every == cfg.moe_every - 1))
            for i in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.drop = Dropout(cfg.dropout)

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = jnp.arange(s)[None, :]
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.h:
            x = blk(x)
        x = self.ln_f(x)
        return jnp.einsum("bsh,vh->bsv", x, self.wte.weight)

    def loss(self, input_ids, labels):
        """LM cross-entropy + aux load-balance losses in ONE forward pass:
        right after ``self(input_ids)`` the gates' ``aux_loss`` buffers
        hold THIS pass's traced values (functional_call's bind keeps them
        live for the duration of the call), so no second forward and no
        RNG mismatch between the lm and aux terms."""
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        lm = -jnp.mean(tok)
        return lm + self.cfg.aux_weight * self.gate_aux_loss()

    def gate_aux_loss(self):
        """Sum of the gates' aux buffers from the most recent forward."""
        from ..distributed.moe import BaseGate
        total = jnp.zeros((), jnp.float32)
        for _, sub in self.named_sublayers(include_self=False):
            if isinstance(sub, BaseGate):
                total = total + sub.aux_loss
        return total

    @staticmethod
    def loss_from_logits(logits, labels, buffers, aux_weight: float):
        """Variant for callers holding functional_call's (out, buffers)."""
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        aux = sum(v for k, v in buffers.items() if k.endswith("aux_loss"))
        return -jnp.mean(tok) + aux_weight * aux


def gpt_moe_tiny(**kw) -> GPTMoEConfig:
    return GPTMoEConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=128, num_experts=4,
                        **kw)
