"""T5 encoder-decoder family (reference ecosystem: PaddleNLP t5 modeling;
architecture: Raffel et al. — pre-LN RMS norms, relative position-bucket
attention biases, unscaled dot-product attention, relu/gated FFN).

TPU-native: functional blocks over jnp; the relative-bias tables make the
attention additive-mask path the natural fit (biases fold into the same
[b, h, q, k] additive term the flash kernel's masked path consumes).
Architectural EXACTNESS is oracle-tested against a weight-mapped
`transformers.T5Model` (tests/test_t5.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.container import LayerList

__all__ = ["T5Config", "T5Model", "T5ForConditionalGeneration", "t5_tiny"]


@dataclasses.dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"      # "relu" | "gated-gelu"
    tie_word_embeddings: bool = True
    pad_token_id: int = 0
    decoder_start_token_id: int = 0


class T5LayerNorm(Layer):
    """RMS norm, NO mean subtraction, NO bias; fp32 accumulation (T5)."""

    def __init__(self, hidden_size: int, epsilon: float = 1e-6):
        super().__init__()
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=I.Constant(1.0))
        self.epsilon = epsilon

    def forward(self, x):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.epsilon)
        return (self.weight * out).astype(x.dtype)


def _relative_position_bucket(rel_pos, bidirectional: bool,
                              num_buckets: int, max_distance: int):
    """HF/T5 bucketing: log-spaced distance buckets, mirrored when
    bidirectional (rel_pos = key_pos - query_pos)."""
    ret = 0
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


class _T5Attention(Layer):
    def __init__(self, cfg: T5Config, has_relative_bias: bool,
                 bidirectional: bool):
        super().__init__()
        inner = cfg.num_heads * cfg.d_kv
        self.cfg = cfg
        self.bidirectional = bidirectional
        self.q = Linear(cfg.d_model, inner, bias_attr=False)
        self.k = Linear(cfg.d_model, inner, bias_attr=False)
        self.v = Linear(cfg.d_model, inner, bias_attr=False)
        self.o = Linear(inner, cfg.d_model, bias_attr=False)
        self.attn_drop = Dropout(cfg.dropout_rate)
        self.has_relative_bias = has_relative_bias
        if has_relative_bias:
            self.relative_attention_bias = Embedding(
                cfg.relative_attention_num_buckets, cfg.num_heads)

    def compute_bias(self, q_len: int, k_len: int):
        """[1, h, q, k] additive bias from the bucket table."""
        cfg = self.cfg
        qpos = jnp.arange(q_len)[:, None]
        kpos = jnp.arange(k_len)[None, :]
        buckets = _relative_position_bucket(
            kpos - qpos, self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance)
        vals = self.relative_attention_bias(buckets)   # [q, k, h]
        return jnp.transpose(vals, (2, 0, 1))[None]

    def forward(self, x, kv=None, position_bias=None, mask=None):
        """x [b, q, d]; kv defaults to x (self-attn).  position_bias and
        mask are additive [*, h|1, q, k] terms.  T5: NO 1/sqrt(d_kv)
        scaling."""
        cfg = self.cfg
        kv = x if kv is None else kv
        b, qn, _ = x.shape
        kn = kv.shape[1]
        nh, dk = cfg.num_heads, cfg.d_kv
        q = self.q(x).reshape(b, qn, nh, dk)
        k = self.k(kv).reshape(b, kn, nh, dk)
        v = self.v(kv).reshape(b, kn, nh, dk)
        scores = jnp.einsum("bqhd,bkhd->bhqk",
                            q.astype(jnp.float32), k.astype(jnp.float32))
        if position_bias is not None:
            scores = scores + position_bias
        if mask is not None:
            scores = scores + mask
        probs = jax.nn.softmax(scores, axis=-1)
        # reference applies dropout to the attention PROBABILITIES too
        probs = self.attn_drop(probs)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs,
                         v.astype(jnp.float32)).astype(x.dtype)
        return self.o(ctx.reshape(b, qn, nh * dk))


class _T5FF(Layer):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        if cfg.feed_forward_proj.startswith("gated"):
            self.wi_0 = Linear(cfg.d_model, cfg.d_ff, bias_attr=False)
            self.wi_1 = Linear(cfg.d_model, cfg.d_ff, bias_attr=False)
        else:
            self.wi = Linear(cfg.d_model, cfg.d_ff, bias_attr=False)
        self.wo = Linear(cfg.d_ff, cfg.d_model, bias_attr=False)

    def forward(self, x):
        if self.cfg.feed_forward_proj.startswith("gated"):
            h = F.gelu(self.wi_0(x), approximate=True) * self.wi_1(x)
        else:
            h = F.relu(self.wi(x))
        return self.wo(h)


class _T5Block(Layer):
    def __init__(self, cfg: T5Config, is_decoder: bool,
                 has_relative_bias: bool):
        super().__init__()
        self.is_decoder = is_decoder
        self.self_attn = _T5Attention(
            cfg, has_relative_bias, bidirectional=not is_decoder)
        self.self_norm = T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon)
        if is_decoder:
            self.cross_attn = _T5Attention(cfg, False, bidirectional=True)
            self.cross_norm = T5LayerNorm(cfg.d_model,
                                          cfg.layer_norm_epsilon)
        self.ff = _T5FF(cfg)
        self.ff_norm = T5LayerNorm(cfg.d_model, cfg.layer_norm_epsilon)
        self.drop = Dropout(cfg.dropout_rate)

    def forward(self, x, enc=None, position_bias=None, self_mask=None,
                cross_mask=None):
        x = x + self.drop(self.self_attn(self.self_norm(x),
                                         position_bias=position_bias,
                                         mask=self_mask))
        if self.is_decoder:
            x = x + self.drop(self.cross_attn(self.cross_norm(x), kv=enc,
                                              mask=cross_mask))
        return x + self.drop(self.ff(self.ff_norm(x)))


class _T5Stack(Layer):
    def __init__(self, cfg: T5Config, is_decoder: bool, n_layers: int):
        super().__init__()
        self.cfg = cfg
        self.is_decoder = is_decoder
        self.block = LayerList([
            _T5Block(cfg, is_decoder, has_relative_bias=(i == 0))
            for i in range(n_layers)])
        self.final_layer_norm = T5LayerNorm(cfg.d_model,
                                            cfg.layer_norm_epsilon)
        self.drop = Dropout(cfg.dropout_rate)

    def forward(self, x, enc=None, attention_mask=None, enc_mask=None):
        b, s, _ = x.shape
        # shared relative bias computed once from block 0 (T5 convention)
        bias = self.block[0].self_attn.compute_bias(s, s)
        self_mask = None
        if self.is_decoder:
            causal = jnp.tril(jnp.ones((s, s), bool))
            self_mask = jnp.where(causal, 0.0, -1e9)[None, None]
        if attention_mask is not None:
            am = (1.0 - jnp.asarray(attention_mask, jnp.float32)) * -1e9
            am = am[:, None, None, :]
            self_mask = am if self_mask is None else self_mask + am
        cross_mask = None
        if enc_mask is not None:
            cm = (1.0 - jnp.asarray(enc_mask, jnp.float32)) * -1e9
            cross_mask = cm[:, None, None, :]
        x = self.drop(x)
        for blk in self.block:
            x = blk(x, enc=enc, position_bias=bias, self_mask=self_mask,
                    cross_mask=cross_mask)
        return self.drop(self.final_layer_norm(x))


class T5Model(Layer):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        self.shared = Embedding(cfg.vocab_size, cfg.d_model)
        self.encoder = _T5Stack(cfg, is_decoder=False,
                                n_layers=cfg.num_layers)
        self.decoder = _T5Stack(cfg, is_decoder=True,
                                n_layers=cfg.num_decoder_layers)

    def encode(self, input_ids, attention_mask=None):
        return self.encoder(self.shared(input_ids),
                            attention_mask=attention_mask)

    def forward(self, input_ids, decoder_input_ids, attention_mask=None,
                decoder_attention_mask=None):
        """Returns (decoder_hidden [b, td, d], encoder_hidden [b, te, d])."""
        enc = self.encode(input_ids, attention_mask)
        dec = self.decoder(self.shared(decoder_input_ids), enc=enc,
                           attention_mask=decoder_attention_mask,
                           enc_mask=attention_mask)
        return dec, enc


class T5ForConditionalGeneration(Layer):
    def __init__(self, cfg: T5Config):
        super().__init__()
        self.cfg = cfg
        self.t5 = T5Model(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = Linear(cfg.d_model, cfg.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, decoder_input_ids, **kw):
        dec, _ = self.t5(input_ids, decoder_input_ids, **kw)
        if self.cfg.tie_word_embeddings:
            # T5 rescales tied logits by d_model^-0.5
            dec = dec * (self.cfg.d_model ** -0.5)
            return jnp.einsum("bsd,vd->bsv", dec, self.t5.shared.weight)
        return self.lm_head(dec)

    def loss(self, input_ids, decoder_input_ids, labels, **kw):
        logits = self(input_ids, decoder_input_ids, **kw)
        return F.cross_entropy(
            logits.reshape(-1, self.cfg.vocab_size),
            jnp.asarray(labels).reshape(-1), ignore_index=-100)


def t5_tiny(**kw) -> T5Config:
    return T5Config(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                    num_layers=2, num_decoder_layers=2, num_heads=4,
                    relative_attention_num_buckets=8,
                    relative_attention_max_distance=20,
                    dropout_rate=0.0, **kw)
