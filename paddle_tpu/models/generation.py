"""Autoregressive generation as ONE compiled loop.

Reference analog: the decoding loop the reference serves through
``fused_multi_transformer`` + PaddleNLP's ``model.generate`` (greedy /
sampling with temperature, top-k, top-p, eos early-stop).

TPU-native design: the whole token-by-token loop is a single
``lax.scan`` over the functional KV-cache ``decode_step`` — one compiled
program for the entire generation instead of one dispatch per token
(per-dispatch latency dominates small decode steps on a remote-attached
chip; the same lesson as scripts/tpu_microbench).  The prompt is
prefilled in one chunked ``decode_step`` call (causal within the chunk),
then the scan carries ``(caches, last_token, position, rng, finished)``;
shapes are static throughout (``max_new_tokens`` is a trace-time int).

Works on any model exposing ``init_cache(batch, max_len)`` and
``decode_step(input_ids, caches, position)`` (GPTForCausalLM,
LlamaForCausalLM).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["generate", "beam_search"]


def _filter_top_k(logits, k: int):
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filter_top_p(logits, p: float):
    """Nucleus filtering: keep the smallest prefix of the probability-
    sorted vocab whose mass reaches ``p`` (the top token always stays)."""
    sorted_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sorted_idx, -1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # token i is kept while the mass BEFORE it is < p
    before = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = before < p
    inv = jnp.argsort(sorted_idx, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, -1)
    return jnp.where(keep, logits, -jnp.inf)


def generate(model, input_ids, max_new_tokens: int, do_sample: bool = False,
             temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None,
             pad_token_id: Optional[int] = None, seed: int = 0,
             output_scores: bool = False, prompt_lens=None):
    """Generate ``max_new_tokens`` continuations of ``input_ids``
    ([batch, prompt_len], dense — no padding) and return the full
    sequences [batch, prompt_len + max_new_tokens].

    ``do_sample=False`` is greedy; sampling applies ``temperature`` then
    ``top_k`` (0 = off) then ``top_p`` (1.0 = off).  With
    ``eos_token_id`` set, rows that emit it keep emitting
    ``pad_token_id`` (default: the eos id) for the remaining steps.
    ``output_scores=True`` additionally returns the pre-sampling float32
    logits of every generated position [batch, max_new_tokens, vocab].

    ``prompt_lens`` ([batch] int32, optional) admits RAGGED right-padded
    prompts: row r's real prompt is ``input_ids[r, :prompt_lens[r]]``.
    Prefill masks the pad tail (the ragged decode-attention seq_lens mask
    — pad keys are never attended by live queries) and each row's decode
    starts at its OWN length, overwriting the pad region of the cache
    token by token.  The generated tokens still land in the trailing
    ``max_new_tokens`` columns of the result; row r's true sequence is
    ``concat(input_ids[r, :prompt_lens[r]], result[r, prompt_len:])``.
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if do_sample and temperature <= 0:
        raise ValueError("temperature must be > 0 when sampling")
    b, s0 = input_ids.shape
    max_seq = getattr(getattr(model, "cfg", None), "max_seq_len", None)
    if max_seq is not None and s0 + max_new_tokens > max_seq:
        raise ValueError(
            f"prompt_len {s0} + max_new_tokens {max_new_tokens} exceeds "
            f"the model's max_seq_len {max_seq} (position table size) — "
            "out-of-range positions would silently clamp")
    input_ids = jnp.asarray(input_ids)
    pad = eos_token_id if pad_token_id is None else pad_token_id
    if prompt_lens is not None:
        lens = jnp.asarray(prompt_lens, jnp.int32)
        if lens.shape != (b,):
            raise ValueError(f"prompt_lens must be [{b}], got {lens.shape}")
        import numpy as _np
        if not isinstance(lens, jax.core.Tracer):
            host = _np.asarray(lens)
            if host.min() < 1 or host.max() > s0:
                raise ValueError("prompt_lens entries must lie in "
                                 f"[1, {s0}]")

    def pick(key, logits):
        logits = logits.astype(jnp.float32)
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(input_ids.dtype)
        logits = logits / temperature
        if top_k:
            logits = _filter_top_k(logits, top_k)
        if top_p < 1.0:
            logits = _filter_top_p(logits, top_p)
        return jax.random.categorical(key, logits,
                                      axis=-1).astype(input_ids.dtype)

    caches = model.init_cache(b, s0 + max_new_tokens)
    logits, caches = model.decode_step(input_ids, caches, 0)
    if prompt_lens is None:
        last_logits = logits[:, -1]
    else:
        # each row's last VALID prompt position carries its next-token
        # distribution; pad-tail logits are garbage and are skipped
        last_logits = jnp.take_along_axis(
            logits, (lens - 1)[:, None, None], axis=1)[:, 0]
    first_scores = last_logits.astype(jnp.float32)
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    first = pick(sub, last_logits)
    if eos_token_id is not None:
        finished = first == eos_token_id
    else:
        finished = jnp.zeros((b,), bool)

    def body(carry, _):
        caches, tok, pos, key, finished = carry
        # ``pos`` is the sequence index of ``tok``, the token being fed
        # (a [b] vector when prompts are ragged — each row decodes at its
        # own offset; models/kv_cache.py handles the per-row cache write)
        logits, caches = model.decode_step(tok[:, None], caches, pos)
        key, sub = jax.random.split(key)
        scores = logits[:, 0].astype(jnp.float32)
        nxt = pick(sub, logits[:, 0])
        if eos_token_id is not None:
            nxt = jnp.where(finished, jnp.asarray(pad, nxt.dtype), nxt)
            finished = finished | (nxt == eos_token_id)
        return (caches, nxt, pos + 1, key, finished), (nxt, scores)

    if prompt_lens is not None:
        # prefill ran at scalar offset 0, so each layer's cache tuple
        # carries the scalar position s0; re-anchor it to the per-row
        # lengths so decode WRITES land at each row's own offset and the
        # attention lens mask the pad tail (models/kv_cache.py semantics)
        caches = [(c[0], c[1], lens) for c in caches]
    if max_new_tokens > 1:
        # ``first`` sits at sequence index s0 (row r: prompt_lens[r]) —
        # that is the position the first scan step feeds it at
        pos0 = jnp.asarray(s0, jnp.int32) if prompt_lens is None else lens
        carry = (caches, first, pos0, key, finished)
        _, (rest, rest_scores) = jax.lax.scan(body, carry, None,
                                              length=max_new_tokens - 1)
        new_tokens = jnp.concatenate(
            [first[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
        scores = jnp.concatenate(
            [first_scores[:, None], jnp.moveaxis(rest_scores, 0, 1)], axis=1)
    else:
        new_tokens = first[:, None]
        scores = first_scores[:, None]
    seq = jnp.concatenate([input_ids, new_tokens], axis=1)
    return (seq, scores) if output_scores else seq


def _repeat_beams(tree, k: int, batch: int):
    """Tile every batch-leading leaf of a cache pytree k times
    ([b, ...] -> [b*k, ...]); scalars (e.g. position counters) pass
    through."""
    def leaf(a):
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] == batch:
            return jnp.repeat(a, k, axis=0)
        return a
    return jax.tree_util.tree_map(leaf, tree)


def _gather_beams(tree, flat_idx, bk: int):
    """Reorder batch-leading leaves by ancestor beam indices."""
    def leaf(a):
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] == bk:
            return jnp.take(a, flat_idx, axis=0)
        return a
    return jax.tree_util.tree_map(leaf, tree)


def beam_search(model, input_ids, max_new_tokens: int, beam_size: int = 4,
                length_penalty: float = 0.0,
                eos_token_id: Optional[int] = None,
                pad_token_id: Optional[int] = None):
    """Beam-search decoding as ONE compiled loop (the expansion step and
    ancestor reordering live inside a single ``lax.scan``; KV caches are
    tiled to ``batch*beam`` rows and gathered per step by beam index).

    Reference analog: the beam decode the reference ships through
    ``nn.BeamSearchDecoder`` / PaddleNLP ``model.generate(
    decode_strategy='beam_search')``.  Finished beams (emitted
    ``eos_token_id``) are frozen: they continue with ``pad_token_id``
    (default: eos) at no score change.  Final ranking uses
    ``score / (n_generated ** length_penalty)`` (0 = raw log-prob).

    Returns ``(sequences [batch, prompt+max_new], scores [batch])`` for
    the best beam of each batch row.

    Cache contract: beam tiling/reordering identifies batch-leading cache
    leaves by ``shape[0] == batch`` — every cache leaf must either lead
    with the batch dimension or have a leading dim different from the
    batch size (a non-batch leaf whose leading dim coincidentally equals
    the batch would be mis-tiled; the shipped GPT/Llama caches satisfy
    the contract by construction).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if beam_size < 1:
        raise ValueError("beam_size must be >= 1")
    b, s0 = input_ids.shape
    k = beam_size
    max_seq = getattr(getattr(model, "cfg", None), "max_seq_len", None)
    if max_seq is not None and s0 + max_new_tokens > max_seq:
        raise ValueError(
            f"prompt_len {s0} + max_new_tokens {max_new_tokens} exceeds "
            f"the model's max_seq_len {max_seq}")
    input_ids = jnp.asarray(input_ids)
    pad = eos_token_id if pad_token_id is None else pad_token_id
    if pad is None:
        pad = 0  # buffer fill only; without eos every slot is written

    # prefill once at batch b, then tile caches to b*k beam rows
    caches = model.init_cache(b, s0 + max_new_tokens)
    logits, caches = model.decode_step(input_ids, caches, 0)
    logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
    vocab = logp.shape[-1]
    # the prefill can seed at most `vocab` distinct beams; wider widths
    # (e.g. an exhaustive beam in tests) fill the rest with -inf scores
    # that real candidates displace in later expansion steps
    k0 = min(k, vocab)
    scores, first = jax.lax.top_k(logp, k0)          # [b, k0] each
    if k0 < k:
        scores = jnp.concatenate(
            [scores, jnp.full((b, k - k0), -jnp.inf, scores.dtype)], 1)
        first = jnp.concatenate(
            [first, jnp.repeat(first[:, :1], k - k0, axis=1)], 1)
    caches = _repeat_beams(caches, k, b)
    bk = b * k

    tokens0 = jnp.full((b, k, max_new_tokens), pad, input_ids.dtype)
    tokens0 = tokens0.at[:, :, 0].set(first.astype(input_ids.dtype))
    if eos_token_id is not None:
        finished0 = first == eos_token_id
    else:
        finished0 = jnp.zeros((b, k), bool)

    def body(carry, t):
        caches, tokens, last, scores, finished = carry
        # ``last`` (buffer slot t-1) sits at sequence index s0 + t - 1 —
        # that is the position it must be fed at (same convention the
        # review pinned for generate())
        logits, caches = model.decode_step(
            last.reshape(bk, 1), caches, s0 + t - 1)
        logp = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32), -1).reshape(b, k, vocab)
        if eos_token_id is not None:
            # frozen beams: exactly one zero-cost continuation slot, all
            # else -inf.  The slot's INDEX is clamped into vocab (pad may
            # legitimately sit past the base vocab — appended pad ids);
            # the actually-emitted token is rewritten to ``pad`` below,
            # so the clamp never leaks into the output.
            slot = min(pad, vocab - 1)
            frozen = jnp.full((vocab,), -jnp.inf).at[slot].set(0.0)
            logp = jnp.where(finished[..., None], frozen, logp)
        cand = scores[..., None] + logp               # [b, k, V]
        scores, idx = jax.lax.top_k(cand.reshape(b, k * vocab), k)
        beam_idx = idx // vocab                       # ancestor beam
        tok = (idx % vocab).astype(tokens.dtype)      # new token
        flat = (jnp.arange(b)[:, None] * k + beam_idx).reshape(-1)
        caches = _gather_beams(caches, flat, bk)
        tokens = jnp.take_along_axis(tokens, beam_idx[..., None], axis=1)
        if eos_token_id is not None:
            prev_finished = jnp.take_along_axis(finished, beam_idx, axis=1)
            tok = jnp.where(prev_finished, jnp.asarray(pad, tok.dtype), tok)
            finished = prev_finished | (tok == eos_token_id)
        tokens = tokens.at[:, :, t].set(tok)
        return (caches, tokens, tok, scores, finished), None

    carry = (caches, tokens0, first.astype(input_ids.dtype), scores,
             finished0)
    if max_new_tokens > 1:
        carry, _ = jax.lax.scan(body, carry,
                                jnp.arange(1, max_new_tokens))
    _, tokens, _, scores, _ = carry

    if length_penalty != 0.0:
        if eos_token_id is not None:
            # generated length up to and including the first eos
            pos = jnp.argmax(tokens == eos_token_id, axis=-1)
            has = jnp.any(tokens == eos_token_id, axis=-1)
            n_gen = jnp.where(has, pos + 1, max_new_tokens)
        else:
            n_gen = jnp.full((b, k), max_new_tokens)
        final = scores / (n_gen.astype(jnp.float32) ** length_penalty)
    else:
        final = scores
    best = jnp.argmax(final, axis=1)                  # [b]
    best_tokens = jnp.take_along_axis(
        tokens, best[:, None, None], axis=1)[:, 0]    # [b, max_new]
    best_scores = jnp.take_along_axis(final, best[:, None], axis=1)[:, 0]
    seq = jnp.concatenate(
        [input_ids, best_tokens.astype(input_ids.dtype)], axis=1)
    return seq, best_scores
