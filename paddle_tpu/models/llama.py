"""Llama family — the semi-auto-parallel flagship (BASELINE config #4).

Reference model surface: the semi-auto Llama used by
test/auto_parallel/hybrid_strategy/ (semi-auto Llama-2 tests, SURVEY.md §4)
and PaddleNLP's LlamaForCausalLM: RMSNorm, rotary position embeddings,
grouped-query attention, SwiGLU MLP, no biases, untied lm_head.

TPU-native design: the model is written as plain Layers (no hand-rolled
parallel layers) and parallelised the semi-auto way —
``llama_shard_fn(mesh)`` places weights via dist.shard_tensor and GSPMD
partitions the jitted step (SURVEY.md §3.4; the reference path
dist.shard_tensor -> DistTensor -> SPMD rules + reshard is all inside XLA
here).  For the hand-written hybrid path, GPT (models/gpt.py) is the
flagship; Llama is the auto-parallel one, mirroring how the reference
splits its two baselines.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.container import LayerList
from ..nn.layers.norm import RMSNorm

__all__ = ["LlamaConfig", "LlamaAttention", "LlamaMLP", "LlamaDecoderLayer",
           "LlamaModel", "LlamaForCausalLM", "llama_shard_fn", "llama_tiny",
           "llama_7b"]


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None      # None -> MHA; < num_heads -> GQA
    max_seq_len: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dropout: float = 0.0
    dtype: str = "float32"
    # False | True (full jax.checkpoint) | a
    # jax.checkpoint_policies name (shared remat_wrap knob)
    remat: "bool | str" = False

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        h, l, v = self.hidden_size, self.num_layers, self.vocab_size
        kvh = self.kv_heads * self.head_dim
        attn = h * h + 2 * h * kvh + h * h          # q, k, v, o
        mlp = 3 * h * self.intermediate_size        # gate, up, down
        norms = 2 * h
        return 2 * v * h + l * (attn + mlp + norms) + h


def _rope_tables(positions, head_dim: int, theta: float, dtype):
    """cos/sin tables [*, head_dim/2] for the given positions."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv  # [..., d/2]
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def apply_rotary_pos_emb(x, cos, sin):
    """x [b, s, heads, d]; cos/sin [s, d/2] (shared positions) or
    [b, s, d/2] (per-row positions — ragged continuous batching).  Llama
    pairing: (x1, x2) = halves (reference fused_rope neox-style)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class LlamaAttention(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        h, d = cfg.hidden_size, cfg.head_dim
        self.q_proj = Linear(h, cfg.num_heads * d, bias_attr=False)
        self.k_proj = Linear(h, cfg.kv_heads * d, bias_attr=False)
        self.v_proj = Linear(h, cfg.kv_heads * d, bias_attr=False)
        self.o_proj = Linear(cfg.num_heads * d, h, bias_attr=False)

    def forward(self, x, cos, sin, cache=None):
        cfg = self.cfg
        b, s, _ = x.shape
        q = self.q_proj(x).reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = self.k_proj(x).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        v = self.v_proj(x).reshape(b, s, cfg.kv_heads, cfg.head_dim)
        q = apply_rotary_pos_emb(q, cos, sin)
        k = apply_rotary_pos_emb(k, cos, sin)
        new_cache = None
        if cache is not None:
            pk, pv, pos = cache
            # pos may be a scalar (dense batch) or a [b] vector of per-row
            # offsets (ragged continuous batching) — models/kv_cache.py
            from .kv_cache import append_kv
            k, v = append_kv(pk, pv, k, v, pos)
            new_cache = (k, v, pos + s)
        # GQA: repeat kv heads up to q heads (XLA turns this into a
        # broadcast inside the attention einsum — no real copy)
        rep = cfg.num_heads // k.shape[2]
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if cache is not None:
            # routed decode attention (see gpt.py _attn): seq_lens =
            # pos + s with the causal tail IS the per-query chunked-
            # prefill mask, with no [*, s, S_max] mask materialization.
            # lens derive from the cache POSITION per row (a scalar pos
            # broadcasts; a [b] vector keeps each row's own context
            # length — ragged batches were silently wrong under the old
            # jnp.full((b,), pos + s) which assumed uniform lengths)
            from ..kernels.decode_attention import decode_attention_auto
            from .kv_cache import cache_lens
            out = decode_attention_auto(q, k, v, cache_lens(cache[2], s, b))
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                                 training=self.training)
        out = out.reshape(b, s, cfg.num_heads * cfg.head_dim)
        return self.o_proj(out), new_cache


class LlamaMLP(Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        self.gate_proj = Linear(h, m, bias_attr=False)
        self.up_proj = Linear(h, m, bias_attr=False)
        self.down_proj = Linear(m, h, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.input_layernorm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = RMSNorm(cfg.hidden_size,
                                                epsilon=cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)
        self.drop = Dropout(cfg.dropout)

    def forward(self, x, cos, sin, cache=None):
        a, new_cache = self.self_attn(self.input_layernorm(x), cos, sin, cache)
        x = x + self.drop(a)
        x = x + self.drop(self.mlp(self.post_attention_layernorm(x)))
        if cache is not None:
            return x, new_cache
        return x

    def fused_decode_step(self, x, cos_full, sin_full, cache):
        """One decode token through the fused decode-block kernel pair
        (kernels/decode_block.py): RMSNorm -> QKV (+rotary) -> in-kernel
        KV append -> GQA streaming attention -> o_proj -> SwiGLU MLP.
        ``cos_full``/``sin_full`` are [B, head_dim] full-width rotary
        tables (halves duplicated) at each row's position; the KV slabs
        in ``cache`` update in place via kernel aliasing."""
        from ..kernels.decode_block import decode_block_layer
        cfg = self.cfg
        pk, pv, pos = cache
        at, mlp = self.self_attn, self.mlp
        y, k2, v2 = decode_block_layer(
            x, pk, pv, pos, kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
            norm="rms", eps1=cfg.rms_norm_eps, eps2=cfg.rms_norm_eps,
            norm1_w=self.input_layernorm.weight, norm1_b=None,
            wq=at.q_proj.weight, wk=at.k_proj.weight, wv=at.v_proj.weight,
            bq=None, bkv=None, bv=None,
            wo=at.o_proj.weight, bo=None,
            norm2_w=self.post_attention_layernorm.weight, norm2_b=None,
            w1=mlp.up_proj.weight, b1=None,
            w2=mlp.down_proj.weight, b2=None,
            w_gate=mlp.gate_proj.weight,
            rope_cos=cos_full, rope_sin=sin_full)
        return y, (k2, v2, pos + 1)


class LlamaModel(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.embed_tokens = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = LayerList([LlamaDecoderLayer(cfg)
                                 for _ in range(cfg.num_layers)])
        self.norm = RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)

    def forward(self, input_ids, caches=None, position_offset: int = 0):
        cfg = self.cfg
        b, s = input_ids.shape
        x = self.embed_tokens(input_ids)
        # offset + static arange: position_offset may be traced (generate);
        # a [b] offset vector gives per-row positions (ragged batching)
        pos = jnp.asarray(position_offset)[..., None] + jnp.arange(s)
        cos, sin = _rope_tables(pos, cfg.head_dim, cfg.rope_theta, x.dtype)
        new_caches = []
        for i, layer in enumerate(self.layers):
            if caches is None:
                from ..distributed.recompute import remat_wrap
                x = remat_wrap(lambda x_, lyr=layer: lyr(x_, cos, sin),
                               cfg.remat)(x)
            else:
                x, c = layer(x, cos, sin, caches[i])
                new_caches.append(c)
        x = self.norm(x)
        return x if caches is None else (x, new_caches)


class LlamaForCausalLM(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.llama = LlamaModel(cfg)
        self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size, bias_attr=False)

    def forward(self, input_ids):
        return self.lm_head(self.llama(input_ids))

    def loss(self, input_ids, labels):
        logits = self(input_ids).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(tok)

    def chunked_loss(self, input_ids, labels, n_chunks: int = 8):
        """Causal LM loss without materializing [b, s, V] logits (the
        chunked-vocab head+CE — see GPTForCausalLM.chunked_loss).  The
        untied lm_head's [h, V] weight enters transposed; XLA fuses the
        transpose into the chunk matmuls."""
        from ..nn.functional import chunked_softmax_cross_entropy
        hidden = self.llama(input_ids)
        b, s, h = hidden.shape
        per_tok = chunked_softmax_cross_entropy(
            hidden.reshape(b * s, h), self.lm_head.weight.T,
            labels.reshape(-1), n_chunks=n_chunks)
        return jnp.mean(per_tok)

    # ---- incremental decode -------------------------------------------
    def init_cache(self, batch: int, max_len: int, dtype=None):
        cfg = self.cfg
        dt = jnp.dtype(dtype or cfg.dtype)
        return [(jnp.zeros((batch, max_len, cfg.kv_heads, cfg.head_dim), dt),
                 jnp.zeros((batch, max_len, cfg.kv_heads, cfg.head_dim), dt),
                 jnp.asarray(0, jnp.int32)) for _ in range(cfg.num_layers)]

    def decode_step(self, input_ids, caches, position: int):
        hidden, new_caches = self.llama(input_ids, caches,
                                        position_offset=position)
        return self.lm_head(hidden), new_caches

    def fused_decode_supported(self, batch: int = 1,
                               kv_len: Optional[int] = None,
                               tp: int = 1):
        """Static legality of the fused decode-block path (GQA aware);
        ``tp > 1`` checks the sharded variant's per-shard plan
        (kernels/decode_block_tp.py).  Returns ``(ok, reason)``."""
        from ..kernels.decode_block import fusion_legal
        cfg = self.cfg
        if cfg.dropout and self.training:
            return False, "dropout active (training mode)"
        return fusion_legal(
            max_seq=kv_len or cfg.max_seq_len, hidden=cfg.hidden_size,
            heads=cfg.num_heads, kv_heads=cfg.kv_heads,
            head_dim=cfg.head_dim, ffn=cfg.intermediate_size, batch=batch,
            dtype=cfg.dtype, gated=True, tp=tp)

    def fused_decode_step(self, input_ids, caches, position):
        """``decode_step`` through the fused decode-block kernels —
        shared embed/final-norm/head legs, fused layer bodies, rotary
        tables computed once at each row's position (full-width, halves
        duplicated: the kernel applies rotary in matrix form)."""
        cfg = self.cfg
        x = self.llama.embed_tokens(input_ids)
        pos = jnp.asarray(position, jnp.int32)
        if pos.ndim == 0:
            pos = jnp.full((x.shape[0],), pos, jnp.int32)
        cos, sin = _rope_tables(pos, cfg.head_dim, cfg.rope_theta,
                                jnp.float32)                 # [B, d/2]
        cos_full = jnp.concatenate([cos, cos], axis=-1)
        sin_full = jnp.concatenate([sin, sin], axis=-1)
        new_caches = []
        for layer, cache in zip(self.llama.layers, caches):
            x, c = layer.fused_decode_step(x, cos_full, sin_full, cache)
            new_caches.append(c)
        x = self.llama.norm(x)
        return self.lm_head(x), new_caches

    def generate(self, input_ids, max_new_tokens: int, **kw):
        """Single-scan autoregressive decoding (models/generation.py)."""
        from .generation import generate
        return generate(self, input_ids, max_new_tokens, **kw)

    # ---- tensor-parallel serving (serving/tp.py) ----------------------
    def tp_decode_supported(self, tp: int):
        """Static legality of the fused compute-collective TP decode
        program at degree ``tp`` (GQA aware: the kv-head axis must tile
        the mesh too, since the KV slot slabs partition on it).
        Returns ``(ok, reason)``."""
        cfg = self.cfg
        for what, n in (("num_heads", cfg.num_heads),
                        ("kv_heads", cfg.kv_heads),
                        ("intermediate_size", cfg.intermediate_size),
                        ("vocab_size", cfg.vocab_size)):
            if n % tp:
                return False, (f"{what} {n} not divisible by "
                               f"tensor_parallel {tp}")
        return True, None

    def tp_decode_weights(self, tp: int):
        """``(arch, weights)`` for the serving TP decode program
        (serving/tp.py): q/k/v column shards re-arranged per device as
        ``[q_d | k_d | v_d]`` head-group blocks (one fused entry
        matmul), gate/up as ``[gate_d | up_d]`` (one fused MLP-up
        matmul); o/down stay row-parallel, embedding/lm_head
        vocab-parallel."""
        cfg = self.cfg
        dh = cfg.head_dim
        arch = {"norm": "rms", "eps": cfg.rms_norm_eps, "act": "swiglu",
                "rope": True, "rope_theta": cfg.rope_theta,
                "heads": cfg.num_heads, "kv_heads": cfg.kv_heads,
                "head_dim": dh, "hidden": cfg.hidden_size,
                "vocab": cfg.vocab_size}
        qs, kvs, fs = ((cfg.num_heads // tp) * dh,
                       (cfg.kv_heads // tp) * dh,
                       cfg.intermediate_size // tp)
        blocks = []
        for layer in self.llama.layers:
            at, mlp = layer.self_attn, layer.mlp
            parts, mparts = [], []
            for d in range(tp):
                parts += [at.q_proj.weight[:, d * qs:(d + 1) * qs],
                          at.k_proj.weight[:, d * kvs:(d + 1) * kvs],
                          at.v_proj.weight[:, d * kvs:(d + 1) * kvs]]
                mparts += [mlp.gate_proj.weight[:, d * fs:(d + 1) * fs],
                           mlp.up_proj.weight[:, d * fs:(d + 1) * fs]]
            blocks.append({
                "n1w": layer.input_layernorm.weight, "n1b": None,
                "wqkv": jnp.concatenate(parts, axis=1), "bqkv": None,
                "wo": at.o_proj.weight, "bo": None,
                "n2w": layer.post_attention_layernorm.weight,
                "n2b": None,
                "wup": jnp.concatenate(mparts, axis=1), "bup": None,
                "wdown": mlp.down_proj.weight, "bdown": None})
        return arch, {
            "wte": self.llama.embed_tokens.weight, "wpe": None,
            "head": self.lm_head.weight,
            "nfw": self.llama.norm.weight, "nfb": None,
            "blocks": blocks}


# ---------------------------------------------------------------------------
# semi-auto sharding plan (reference: the hybrid_strategy llama tests call
# dist.shard_tensor on q/k/v/o and gate/up/down with [Replicate, Shard(...)])
# ---------------------------------------------------------------------------

def llama_shard_fn(mesh, dp_axis: str = "dp", mp_axis: str = "mp"):
    """Build a shard_fn for dist.shard_layer: Megatron-style TP placement
    over ``mp_axis``; everything else replicated (dp comes from the batch).
    """
    from ..distributed.auto_parallel import shard_tensor, Shard, Replicate

    mp_dim = mesh.dim_names.index(mp_axis)

    def place(sub, pname, tensor_dim):
        p = sub._parameters.get(pname)
        if p is None:
            return
        pl = [Replicate()] * mesh.ndim
        pl[mp_dim] = Shard(tensor_dim)
        sub._parameters[pname] = shard_tensor(p, mesh, pl)

    def shard_fn(name, sub, m):
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"):
            place(sub, "weight", 1)   # column parallel: [h, out/mp]
        elif leaf in ("o_proj", "down_proj"):
            place(sub, "weight", 0)   # row parallel: [in/mp, h]
        elif leaf == "embed_tokens":
            place(sub, "weight", 1)   # hidden-sharded embedding
        elif leaf == "lm_head":
            place(sub, "weight", 1)   # vocab-parallel logits

    return shard_fn


def llama_tiny(**kw) -> LlamaConfig:
    return LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=176,
                       num_layers=2, num_heads=4, num_kv_heads=2,
                       max_seq_len=128, **kw)


def llama_7b(**kw) -> LlamaConfig:
    # Llama-2-7B: 32 layers, 4096 hidden, 11008 ffn, 32 heads, MHA
    return LlamaConfig(vocab_size=32000, hidden_size=4096,
                       intermediate_size=11008, num_layers=32, num_heads=32,
                       max_seq_len=4096, dtype="bfloat16", **kw)
