"""GPT — the flagship hybrid-parallel decoder LM (BASELINE config #3).

Reference model surface: the fleet GPT used by
test/collective/fleet/hybrid_parallel* and PaddleNLP's GPT-3 configs —
VocabParallelEmbedding + learned positions, pre-LN blocks with
Column/RowParallelLinear attention+MLP, vocab-parallel loss
(c_softmax_with_cross_entropy), fused_multi_transformer decode path
(paddle/phi/kernels/fusion/gpu — fused_multi_transformer_op.cu).

TPU-native design:
  * weights carry PartitionSpecs (mp for TP; stacked-block leading axis for
    PP) — XLA inserts all collectives;
  * attention routes through F.scaled_dot_product_attention (Pallas flash
    kernel on TPU for long seq);
  * the decode path is a functional KV-cache step (cache in buffers) — the
    fused_multi_transformer equivalent is one jitted decode step whose ops
    XLA fuses; a Pallas fused-block variant lives in paddle_tpu/kernels;
  * ``gpt_train_step_builder`` builds the full dp×mp×pp×sp jitted train
    step used by __graft_entry__.dryrun_multichip and bench.py.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers.container import LayerList
from ..nn.layers.norm import LayerNorm
from ..nn.layers.common import Dropout
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    parallel_cross_entropy, _maybe_constraint)

__all__ = ["GPTConfig", "GPTBlock", "GPTModel", "GPTForCausalLM",
           "gpt_tiny", "gpt_small", "gpt3_6_7b"]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ffn_mult: int = 4
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    dtype: str = "float32"
    use_bias: bool = True
    # parallel/runtime knobs
    sp: bool = False          # sequence-parallel activations between blocks
    # jax.checkpoint per block: False | True (full) | a
    # jax.checkpoint_policies name (e.g. "dots_saveable")
    remat: "bool | str" = True
    # context parallelism over the sep mesh axis: None | "ring" | "ulysses"
    # (reference: sep_degree in hybrid_configs; ring attn from PaddleNLP)
    cp: "str | None" = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self):
        return self.hidden_size * self.ffn_mult

    def num_params(self) -> int:
        h, l, v = self.hidden_size, self.num_layers, self.vocab_size
        per_block = 4 * h * h + 2 * h * self.ffn_size + \
            (9 * h + 2 * self.ffn_size if self.use_bias else 4 * h)
        emb = v * h + self.max_seq_len * h
        head = 0 if self.tie_embeddings else v * h
        return emb + l * per_block + 2 * h + head


class GPTBlock(Layer):
    """Pre-LN transformer decoder block; shape-preserving (pipeline body)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h = cfg.hidden_size
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        # fused qkv: one column-parallel matmul [h, 3h] (reference fuses the
        # same way in fused_attention)
        self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False,
                                        has_bias=cfg.use_bias)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True,
                                          has_bias=cfg.use_bias)
        self.ln_2 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(h, cfg.ffn_size, gather_output=False,
                                          has_bias=cfg.use_bias)
        self.fc_out = RowParallelLinear(cfg.ffn_size, h, input_is_parallel=True,
                                        has_bias=cfg.use_bias)
        self.drop = Dropout(cfg.dropout)

    def _attn(self, x, cache=None):
        cfg = self.cfg
        b, s, h = x.shape
        qkv = self.qkv(x)  # [b, s, 3h] mp-sharded on last dim
        qkv = qkv.reshape(b, s, 3, cfg.num_heads, cfg.head_dim)
        # keep heads mp-sharded: [b, s, heads/mp, d]
        qkv = _maybe_constraint(qkv, P(None, None, None, "mp", None))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        new_cache = None
        if cache is not None:
            pk, pv, pos = cache
            # pos may be a scalar (dense batch) or a [b] vector of per-row
            # offsets (ragged continuous batching) — models/kv_cache.py
            from .kv_cache import append_kv, cache_lens
            k, v = append_kv(pk, pv, k, v, pos)
            new_cache = (k, v, pos + s)
            # decode: the routed decode-attention path (pallas streaming
            # kernel or its exact-semantics dense form, kernels/routing.py)
            # — seq_lens = pos + s with the causal tail gives precisely
            # the per-query mask (query at chunk offset t sees keys up to
            # pos + t), without materializing a [*, s, S_max] mask tensor
            from ..kernels.decode_attention import decode_attention_auto
            out = decode_attention_auto(q, k, v, cache_lens(pos, s, b))
        elif cfg.cp:
            # long-context: sequence sharded over the sep axis; ring or
            # Ulysses attention instead of local sdpa (attn dropout is not
            # supported across the ring, matching the ring-flash reference)
            from ..distributed.meta_parallel.context_parallel import (
                ring_attention, ulysses_attention)
            attn = {"ring": ring_attention, "ulysses": ulysses_attention}[cfg.cp]
            out = attn(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                                 dropout_p=cfg.attn_dropout,
                                                 training=self.training)
        out = out.reshape(b, s, h)
        out = _maybe_constraint(out, P(None, None, "mp"))
        return self.out_proj(out), new_cache

    def forward(self, x, cache=None):
        cfg = self.cfg
        if cfg.sp:
            from ..distributed.meta_parallel.sequence_parallel import seq_sharded
            # LN/dropout run seq-sharded ([b, s/mp, h] — batch-major variant)
            x = _maybe_constraint(x, P(None, "mp", None))
        a, new_cache = self._attn(self.ln_1(x), cache)
        x = x + self.drop(a)
        m = self.fc_out(F.gelu(self.fc_in(self.ln_2(x)), approximate=True))
        x = x + self.drop(m)
        if cache is not None:
            return x, new_cache
        return x

    def fused_decode_step(self, x, cache):
        """One decode token through the fused decode-block kernel pair
        (kernels/decode_block.py): norm -> QKV -> in-kernel KV append ->
        streaming attention -> out-proj -> MLP, activations VMEM-
        resident.  ``cache`` is the slot-slab tuple ``(k, v, pos)`` with
        per-row positions; the slabs are updated in place via kernel
        aliasing.  Same contract as the ``forward(cache=...)`` path for
        sq=1 — callers gate on ``fused_decode_supported``."""
        from ..kernels.decode_block import decode_block_layer
        cfg = self.cfg
        h = cfg.hidden_size
        pk, pv, pos = cache
        wqkv = self.qkv.weight                  # [h, 3h]: q | k | v cols
        bqkv = self.qkv.bias
        bq, bk, bv = ((bqkv[:h], bqkv[h:2 * h], bqkv[2 * h:])
                      if bqkv is not None else (None, None, None))
        y, k2, v2 = decode_block_layer(
            x, pk, pv, pos, kv_heads=cfg.num_heads, head_dim=cfg.head_dim,
            norm="layer", eps1=cfg.layer_norm_eps, eps2=cfg.layer_norm_eps,
            norm1_w=self.ln_1.weight, norm1_b=self.ln_1.bias,
            wq=wqkv[:, :h], wk=wqkv[:, h:2 * h], wv=wqkv[:, 2 * h:],
            bq=bq, bkv=bk, bv=bv,
            wo=self.out_proj.weight, bo=self.out_proj.bias,
            norm2_w=self.ln_2.weight, norm2_b=self.ln_2.bias,
            w1=self.fc_in.weight, b1=self.fc_in.bias,
            w2=self.fc_out.weight, b2=self.fc_out.bias,
            act="gelu_tanh")
        return y, (k2, v2, pos + 1)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = VocabParallelEmbedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = Dropout(cfg.dropout)
        self.h = LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def embed(self, input_ids, position_offset: int = 0):
        b, s = input_ids.shape
        # written as offset + static arange so position_offset may be a
        # traced value (the generate() scan carries it); a [b] offset
        # vector gives per-row positions (ragged continuous batching)
        off = jnp.asarray(position_offset)
        pos = off[..., None] + jnp.arange(s)
        if pos.ndim == 1:
            pos = pos[None, :]
        x = self.wte(input_ids) + self.wpe(pos)
        return self.drop(x)

    def forward(self, input_ids, caches=None):
        from ..distributed.recompute import remat_wrap
        x = self.embed(input_ids)
        new_caches = []
        for i, block in enumerate(self.h):
            if caches is None:
                # cfg.remat applies per block in the training forward
                # (decode/cached path never rematerializes)
                x = remat_wrap(block, self.cfg.remat)(x)
            else:
                x, c = block(x, caches[i])
                new_caches.append(c)
        x = self.ln_f(x)
        return x if caches is None else (x, new_caches)


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = ColumnParallelLinear(cfg.hidden_size, cfg.vocab_size,
                                                gather_output=False,
                                                has_bias=False)

    def logits(self, hidden):
        if self.cfg.tie_embeddings:
            w = self.gpt.wte.weight  # [vocab, h] mp-sharded on vocab
            lg = jnp.einsum("bsh,vh->bsv", hidden, w)
            return _maybe_constraint(lg, P(None, None, "mp"))
        return self.lm_head(hidden)

    def forward(self, input_ids):
        hidden = self.gpt(input_ids)
        return self.logits(hidden)

    def loss(self, input_ids, labels):
        """Vocab-parallel causal LM loss (mean over tokens)."""
        logits = self(input_ids)
        per_tok = parallel_cross_entropy(logits, labels)
        return jnp.mean(per_tok)

    def chunked_loss(self, input_ids, labels, n_chunks: int = 8):
        """Causal LM loss WITHOUT materializing [b, s, V] logits: the
        tied head + softmax CE run chunked over the vocabulary
        (nn.functional.chunked_softmax_cross_entropy).  The single-
        device memory lever: at the flagship bench shape the dense
        logits + grad cost ~3.3 GB of HBM.  Requires tied embeddings
        (the chunked kernel takes the [V, h] table directly)."""
        if not self.cfg.tie_embeddings:
            raise ValueError("chunked_loss needs tie_embeddings=True")
        from ..nn.functional import chunked_softmax_cross_entropy
        hidden = self.gpt(input_ids)
        b, s, h = hidden.shape
        per_tok = chunked_softmax_cross_entropy(
            hidden.reshape(b * s, h), self.gpt.wte.weight,
            labels.reshape(-1), n_chunks=n_chunks)
        return jnp.mean(per_tok)

    # ---- decode (fused_multi_transformer equivalent) -------------------
    def init_cache(self, batch: int, max_len: int, dtype=None):
        cfg = self.cfg
        dt = jnp.dtype(dtype or cfg.dtype)
        return [(jnp.zeros((batch, max_len, cfg.num_heads, cfg.head_dim), dt),
                 jnp.zeros((batch, max_len, cfg.num_heads, cfg.head_dim), dt),
                 jnp.asarray(0, jnp.int32)) for _ in range(cfg.num_layers)]

    def decode_step(self, input_ids, caches, position: int):
        """One incremental token step; returns (logits, new_caches)."""
        x = self.gpt.embed(input_ids, position)
        new_caches = []
        for block, cache in zip(self.gpt.h, caches):
            x, c = block(x, cache)
            new_caches.append(c)
        x = self.gpt.ln_f(x)
        return self.logits(x), new_caches

    def fused_decode_supported(self, batch: int = 1,
                               kv_len: Optional[int] = None,
                               tp: int = 1):
        """Static legality of the fused decode-block path for this
        config at ``(batch, kv_len)``; ``tp > 1`` checks the sharded
        variant's per-shard plan (kernels/decode_block_tp.py).
        Returns ``(ok, reason)``."""
        from ..kernels.decode_block import fusion_legal
        cfg = self.cfg
        if cfg.dropout and self.training:
            return False, "dropout active (training mode)"
        return fusion_legal(
            max_seq=kv_len or cfg.max_seq_len, hidden=cfg.hidden_size,
            heads=cfg.num_heads, kv_heads=cfg.num_heads,
            head_dim=cfg.head_dim, ffn=cfg.ffn_size, batch=batch,
            dtype=cfg.dtype, tp=tp)

    def fused_decode_step(self, input_ids, caches, position):
        """``decode_step`` through the fused decode-block kernels: the
        embed / final-norm / logits legs are shared code, each layer
        body runs as the Pallas kernel pair with the KV slabs updated
        in-kernel.  Per-row ``position`` vectors (continuous batching)
        and scalars both work."""
        x = self.gpt.embed(input_ids, position)
        new_caches = []
        for block, cache in zip(self.gpt.h, caches):
            x, c = block.fused_decode_step(x, cache)
            new_caches.append(c)
        x = self.gpt.ln_f(x)
        return self.logits(x), new_caches

    def generate(self, input_ids, max_new_tokens: int, **kw):
        """Single-scan autoregressive decoding (models/generation.py)."""
        from .generation import generate
        return generate(self, input_ids, max_new_tokens, **kw)

    # ---- tensor-parallel serving (serving/tp.py) ----------------------
    def tp_decode_supported(self, tp: int):
        """Static legality of the fused compute-collective TP decode
        program at degree ``tp``: every partitioned dimension must tile
        the mesh axis evenly (fixed shapes per device — the same
        discipline as the engine's compile-count pin).  Returns
        ``(ok, reason)``."""
        cfg = self.cfg
        for what, n in (("num_heads", cfg.num_heads),
                        ("ffn_size", cfg.ffn_size),
                        ("vocab_size", cfg.vocab_size)):
            if n % tp:
                return False, (f"{what} {n} not divisible by "
                               f"tensor_parallel {tp}")
        return True, None

    def tp_decode_weights(self, tp: int):
        """``(arch, weights)`` for the serving TP decode program
        (serving/tp.py).  The fused QKV weight is re-arranged so each
        device's contiguous column shard is ``[q_d | k_d | v_d]`` for
        its own head group — the manual program needs head-aligned
        blocks, which the training layout's plain contiguous split of
        the fused ``[h, 3h]`` matrix does not give."""
        cfg = self.cfg
        h, dh = cfg.hidden_size, cfg.head_dim
        arch = {"norm": "layer", "eps": cfg.layer_norm_eps,
                "act": "gelu_tanh", "rope": False, "rope_theta": None,
                "heads": cfg.num_heads, "kv_heads": cfg.num_heads,
                "head_dim": dh, "hidden": h, "vocab": cfg.vocab_size}
        step = (cfg.num_heads // tp) * dh
        blocks = []
        for blk in self.gpt.h:
            w, bias = blk.qkv.weight, blk.qkv.bias
            wq, wk, wv = w[:, :h], w[:, h:2 * h], w[:, 2 * h:]
            parts, bparts = [], []
            for d in range(tp):
                sl = slice(d * step, (d + 1) * step)
                parts += [wq[:, sl], wk[:, sl], wv[:, sl]]
                if bias is not None:
                    bparts += [bias[:h][sl], bias[h:2 * h][sl],
                               bias[2 * h:][sl]]
            blocks.append({
                "n1w": blk.ln_1.weight, "n1b": blk.ln_1.bias,
                "wqkv": jnp.concatenate(parts, axis=1),
                "bqkv": jnp.concatenate(bparts) if bias is not None
                else None,
                "wo": blk.out_proj.weight, "bo": blk.out_proj.bias,
                "n2w": blk.ln_2.weight, "n2b": blk.ln_2.bias,
                "wup": blk.fc_in.weight, "bup": blk.fc_in.bias,
                "wdown": blk.fc_out.weight, "bdown": blk.fc_out.bias})
        return arch, {
            "wte": self.gpt.wte.weight, "wpe": self.gpt.wpe.weight,
            "head": None if cfg.tie_embeddings else self.lm_head.weight,
            "nfw": self.gpt.ln_f.weight, "nfb": self.gpt.ln_f.bias,
            "blocks": blocks}


def gpt_tiny(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, **kw)


def gpt_small(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                     num_heads=12, max_seq_len=1024, **kw)


def gpt3_6_7b(**kw) -> GPTConfig:
    # GPT-3 6.7B: 32 layers, 4096 hidden, 32 heads, 2048 seq
    return GPTConfig(vocab_size=50304, hidden_size=4096, num_layers=32,
                     num_heads=32, max_seq_len=2048, dtype="bfloat16", **kw)
