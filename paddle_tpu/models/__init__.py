"""Model zoo (LLM family; vision models live in paddle_tpu.vision.models)."""

from .generation import beam_search, generate  # noqa: F401
from .gpt import (GPTConfig, GPTBlock, GPTModel, GPTForCausalLM,  # noqa: F401
                  gpt_tiny, gpt_small, gpt3_6_7b)
from .trainer import GPTHybridTrainer, GPTMoEHybridTrainer  # noqa: F401
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM,  # noqa: F401
                    LlamaAttention, LlamaMLP, LlamaDecoderLayer,
                    llama_shard_fn, llama_tiny, llama_7b)
from .gpt_moe import (GPTMoEConfig, GPTMoEForCausalLM,  # noqa: F401
                      gpt_moe_tiny)
from .bert import (BertConfig, BertModel, BertForMaskedLM,  # noqa: F401
                   BertForSequenceClassification, bert_tiny)
from .t5 import (T5Config, T5Model, T5ForConditionalGeneration,  # noqa: F401
                 t5_tiny)
