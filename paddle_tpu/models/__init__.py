"""Model zoo (LLM family; vision models live in paddle_tpu.vision.models)."""

from .gpt import (GPTConfig, GPTBlock, GPTModel, GPTForCausalLM,  # noqa: F401
                  gpt_tiny, gpt_small, gpt3_6_7b)
from .trainer import GPTHybridTrainer  # noqa: F401
