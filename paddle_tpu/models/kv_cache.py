"""Shared KV-cache position plumbing for the decoder models.

The functional cache every causal LM here carries is a per-layer tuple
``(k_buf, v_buf, pos)`` with ``k_buf/v_buf [batch, max_len, heads, dim]``.
Historically ``pos`` was a single scalar — every row of the batch sat at
the same context length.  Continuous batching (paddle_tpu.serving) packs
requests of DIFFERENT lengths into one fixed-shape batch, so ``pos`` may
now also be an int32 VECTOR ``[batch]`` of per-row cache positions:

  * scalar ``pos``  — the whole chunk lands at one offset
    (``dynamic_update_slice``), the classic dense-batch decode;
  * vector ``pos``  — row r's chunk lands at ``pos[r]`` (a vmapped
    per-row ``dynamic_update_slice``), and the attention mask uses row
    r's own length.

Both forms stay fixed-shape: the cache buffers never reallocate, only
the write offset and the masking length vary — graftlint's
recompile-hazard rule is the design constraint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["append_kv", "cache_lens"]


def _is_per_row(pos) -> bool:
    return getattr(pos, "ndim", 0) >= 1


def append_kv(pk, pv, k, v, pos):
    """Write the fresh chunk ``k/v [b, s, h, d]`` into the cache buffers
    ``pk/pv [b, max_len, h, d]`` at ``pos`` (scalar, or ``[b]`` int32 for
    per-row offsets).  Returns the updated full buffers."""
    if _is_per_row(pos):
        def row(buf, new, p):
            return jax.lax.dynamic_update_slice_in_dim(buf, new, p, axis=0)
        upd = jax.vmap(row)
        p = jnp.asarray(pos, jnp.int32)
        return upd(pk, k, p), upd(pv, v, p)
    return (jax.lax.dynamic_update_slice_in_dim(pk, k, pos, axis=1),
            jax.lax.dynamic_update_slice_in_dim(pv, v, pos, axis=1))


def cache_lens(pos, s: int, batch: int):
    """Per-row valid cache lengths AFTER appending an ``s``-token chunk at
    ``pos`` — the ``seq_lens`` the ragged decode-attention kernel masks
    by.  A scalar ``pos`` broadcasts to every row; a ``[batch]`` vector is
    each row's own context length (ragged continuous-batching decode)."""
    if _is_per_row(pos):
        return (jnp.asarray(pos, jnp.int32) + s).astype(jnp.int32)
    return jnp.full((batch,), pos + s, jnp.int32)
