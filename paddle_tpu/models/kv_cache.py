"""Shared KV-cache position plumbing for the decoder models.

The functional cache every causal LM here carries is a per-layer tuple
``(k_buf, v_buf, pos)`` with ``k_buf/v_buf [batch, max_len, heads, dim]``.
Historically ``pos`` was a single scalar — every row of the batch sat at
the same context length.  Continuous batching (paddle_tpu.serving) packs
requests of DIFFERENT lengths into one fixed-shape batch, so ``pos`` may
now also be an int32 VECTOR ``[batch]`` of per-row cache positions:

  * scalar ``pos``  — the whole chunk lands at one offset
    (``dynamic_update_slice``), the classic dense-batch decode;
  * vector ``pos``  — row r's chunk lands at ``pos[r]`` (a vmapped
    per-row ``dynamic_update_slice``), and the attention mask uses row
    r's own length.

Both forms stay fixed-shape: the cache buffers never reallocate, only
the write offset and the masking length vary — graftlint's
recompile-hazard rule is the design constraint.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["append_kv", "cache_lens", "gather_block_rows",
           "scatter_block_rows"]


def _is_per_row(pos) -> bool:
    return getattr(pos, "ndim", 0) >= 1


def append_kv(pk, pv, k, v, pos):
    """Write the fresh chunk ``k/v [b, s, h, d]`` into the cache buffers
    ``pk/pv [b, max_len, h, d]`` at ``pos`` (scalar, or ``[b]`` int32 for
    per-row offsets).  Returns the updated full buffers."""
    if _is_per_row(pos):
        def row(buf, new, p):
            return jax.lax.dynamic_update_slice_in_dim(buf, new, p, axis=0)
        upd = jax.vmap(row)
        p = jnp.asarray(pos, jnp.int32)
        return upd(pk, k, p), upd(pv, v, p)
    return (jax.lax.dynamic_update_slice_in_dim(pk, k, pos, axis=1),
            jax.lax.dynamic_update_slice_in_dim(pv, v, pos, axis=1))


def gather_block_rows(block_buf, idx):
    """Assemble a contiguous cache row from block-pool rows: gather
    ``idx`` ([n] int32 block ids, clamped in bounds) out of ``block_buf``
    ([num_blocks, block_len, h, d]) and flatten to ``[n * block_len, h,
    d]`` — the cache-view a slot adopts its shared prefix from.  Entries
    past the true match count gather stale rows; callers mask them via
    the per-row ``seq_lens`` (exactly the slot-reuse discipline of
    ``KVPool``), so no in-kernel validity select is needed."""
    rows = jnp.take(block_buf, jnp.asarray(idx, jnp.int32), axis=0,
                    mode="clip")
    n, bl, h, d = rows.shape
    return rows.reshape(n * bl, h, d)


def scatter_block_rows(block_buf, row, dest):
    """Inverse of :func:`gather_block_rows`: split a contiguous cache row
    ``[n * block_len, h, d]`` into block_len pieces and scatter piece j
    into ``block_buf[dest[j]]``.  ``dest`` entries >= num_blocks are
    DROPPED (out-of-bounds scatter mode) — the one-program way to write
    an arbitrary SUBSET of a prompt's blocks (only the freshly computed
    ones; already-cached prefix blocks stay untouched)."""
    nb, bl, h, d = block_buf.shape
    pieces = row.reshape(-1, bl, h, d)
    return block_buf.at[jnp.asarray(dest, jnp.int32)].set(pieces,
                                                          mode="drop")


def cache_lens(pos, s: int, batch: int):
    """Per-row valid cache lengths AFTER appending an ``s``-token chunk at
    ``pos`` — the ``seq_lens`` the ragged decode-attention kernel masks
    by.  A scalar ``pos`` broadcasts to every row; a ``[batch]`` vector is
    each row's own context length (ragged continuous-batching decode)."""
    if _is_per_row(pos):
        return (jnp.asarray(pos, jnp.int32) + s).astype(jnp.int32)
    return jnp.full((batch,), pos + s, jnp.int32)
