"""Hybrid-parallel GPT trainer: ONE jitted step covering dp, tp(mp), sp,
ZeRO(sharding) and pp.

This is the TPU-native equivalent of the reference's entire fleet hot loop
(SURVEY.md §3.1): fleet.distributed_model + PipelineParallel.train_batch +
DygraphShardingOptimizer.step + EagerReducer allreduces — all of which
become sharding declarations on a single compiled program.

Layout summary (mesh axes [dp, pp, sharding, sep, mp]):
  batch              P(("dp","sharding"))          global batch sharded
  mp weights         P(None,"mp") / P("mp",None)   Megatron TP
  activations        P(dp, None, "mp") at block boundaries when sp=True
  block stack        leading block axis P("pp")    scan+ppermute schedule
  optimizer slots    + "sharding" axis             ZeRO-1
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn import functional as F
from ..nn.functional_call import functional_call, state
from ..distributed.sharding_utils import (get_param_specs, shard_state,
                                          shard_opt_state_specs)
from ..distributed.pipelining import pipeline_apply
from ..distributed.meta_parallel.mp_layers import (parallel_cross_entropy,
                                                   _maybe_constraint)
from .gpt import GPTConfig, GPTForCausalLM

__all__ = ["GPTHybridTrainer", "GPTMoEHybridTrainer"]


from ..distributed.recompute import remat_wrap as _remat_wrap  # noqa: E402


class GPTHybridTrainer:
    # state-layout key map — subclasses (GPTMoEHybridTrainer) remap these
    # to their model's parameter names
    BLOCK_PREFIX = "gpt.h."
    KEY_WTE = "gpt.wte.weight"
    KEY_WPE = "gpt.wpe.weight"
    KEY_LNF_W = "gpt.ln_f.weight"
    KEY_LNF_B = "gpt.ln_f.bias"

    def __init__(self, cfg: GPTConfig, hcg, optimizer, microbatches: int = 1,
                 zero_stage: int = 1, vpp: int = 1):
        self.cfg = cfg
        self.hcg = hcg
        self.mesh = hcg.get_mesh()
        self.opt = optimizer
        self.M = microbatches
        self.S = hcg.get_pipe_parallel_world_size()
        # interleaved (VPP) schedule: V chunks per stage round-robin
        # (reference: PipelineParallelWithInterleave)
        self.V = max(vpp, 1)
        if self.S > 1 and cfg.num_layers % (self.S * self.V):
            raise ValueError(
                f"num_layers={cfg.num_layers} must divide evenly into "
                f"pp_degree={self.S} x vpp={self.V} chunks (reference "
                f"PipelineLayer uniform segmentation has the same "
                f"requirement)")
        if self.V > 1 and self.S > 1 and microbatches % self.S:
            raise ValueError("interleaved schedule needs microbatches "
                             "divisible by pp_degree")
        self.zero = zero_stage
        self.model = self._make_model(cfg)
        dt = getattr(cfg, "dtype", "float32")
        if dt != "float32":
            # cast BEFORE the layout snapshot so the stacked/sharded
            # state carries the configured dtype (masters stay f32 via
            # multi_precision); Layer.to validates the dtype string
            self.model.to(dtype=dt)
        self._build_state_layout()
        self._jit_step = None

    def _make_model(self, cfg):
        return GPTForCausalLM(cfg)

    def _get_template_block(self):
        return self.model.gpt.h[0]

    # ------------------------------------------------------------------
    def _build_state_layout(self):
        params, _ = state(self.model)
        specs = get_param_specs(self.model)
        L = self.cfg.num_layers
        # Stage-assign the embedding/head the SPMD way (reference:
        # meta_parallel/pp_layers.py — SharedLayerDesc ties wte between the
        # first and last stage and allreduces its grad between them).  In
        # the one-program schedule "ownership" is sharding: the vocab (and
        # position) tables extend their row sharding over the pp axis, so
        # each pipeline stage holds 1/S of the table instead of a full
        # replica, and the tied-weight grad merge (embed use + head use)
        # falls out of AD + GSPMD as exactly the reference's allreduce.
        wte_spec = tuple(specs[self.KEY_WTE])
        self._vocab_axes = wte_spec[0] if wte_spec else None
        import os as _os
        if self.S > 1 and _os.environ.get("PADDLE_TPU_PP_EXTEND_EMBED",
                                          "1") == "1":
            for k in (self.KEY_WTE, self.KEY_WPE):
                if k in specs:
                    old = tuple(specs[k])  # P(mp, None) from the embedding
                    d0 = old[0] if old else None
                    if d0 is None:
                        d0 = "pp"
                    elif isinstance(d0, tuple):
                        d0 = d0 + ("pp",)
                    else:
                        d0 = (d0, "pp")
                    specs[k] = P(d0, *old[1:])
            self._vocab_axes = specs[self.KEY_WTE][0]
        self.block_names = []   # suffix names within a block
        nonblock, blocks0 = {}, {}
        for k, v in params.items():
            if k.startswith(self.BLOCK_PREFIX):
                rest = k[len(self.BLOCK_PREFIX):]
                idx, suffix = rest.split(".", 1)
                if idx == "0":
                    blocks0[suffix] = None
            else:
                nonblock[k] = v
        self.block_names = sorted(blocks0)
        # stacked block params: [L, ...] for the plain schedule; for VPP,
        # [S*V, K, ...] with the chunk dim in stack_interleaved order
        # (device s's P('pp') slice = its round-robin chunks) and K = blocks
        # per chunk scanned by the stage body
        stacked = {}
        stacked_specs = {}
        interleave = self.S > 1 and self.V > 1
        K = L // (self.S * self.V) if interleave else None
        for suffix in self.block_names:
            per = [params[f"{self.BLOCK_PREFIX}{i}.{suffix}"]
                   for i in range(L)]
            inner = specs.get(f"{self.BLOCK_PREFIX}0.{suffix}", P())
            if interleave:
                order = [v * self.S + s for s in range(self.S)
                         for v in range(self.V)]
                stacked[suffix] = jnp.stack(
                    [jnp.stack(per[c * K:(c + 1) * K], axis=0)
                     for c in order], axis=0)
                stacked_specs[suffix] = P("pp", None, *tuple(inner))
            else:
                stacked[suffix] = jnp.stack(per, axis=0)
                stacked_specs[suffix] = P("pp" if self.S > 1 else None,
                                          *tuple(inner))
        self.params_nonblock = nonblock
        self.params_blocks = stacked
        self.specs_nonblock = {k: specs.get(k, P()) for k in nonblock}
        self.specs_blocks = stacked_specs
        self.template_block = self._get_template_block()

        # ZeRO slot specs (stage >= 1) — also grad specs for stage >= 2 and
        # param specs for stage 3 (reference: GroupShardedStage2/3 grad
        # reduce-scatter + param gather-on-use; here: sharding declarations
        # XLA lowers to exactly that collective pattern)
        shard_deg = self.hcg.get_sharding_parallel_world_size()
        if shard_deg > 1:
            self.slot_specs_nb = shard_opt_state_specs(
                self.specs_nonblock,
                {k: tuple(v.shape) for k, v in nonblock.items()},
                "sharding", shard_deg)
            self.slot_specs_blk = shard_opt_state_specs(
                self.specs_blocks,
                {k: tuple(v.shape) for k, v in stacked.items()},
                "sharding", shard_deg)
        else:
            self.slot_specs_nb = self.specs_nonblock
            self.slot_specs_blk = self.specs_blocks
        if self.zero >= 3 and shard_deg > 1:
            # stage 3: parameters THEMSELVES live sharded; GSPMD inserts
            # the all-gather at each use site
            self.specs_nonblock = self.slot_specs_nb
            self.specs_blocks = self.slot_specs_blk

    def batch_spec(self):
        axes = []
        if self.hcg.get_data_parallel_world_size() > 1:
            axes.append("dp")
        if self.hcg.get_sharding_parallel_world_size() > 1:
            axes.append("sharding")
        return P(tuple(axes) if axes else None)

    # ------------------------------------------------------------------
    def init_state(self):
        """Returns (params_nonblock, params_blocks, opt_nb, opt_blk) laid out
        on the mesh."""
        mesh = self.mesh
        pnb = shard_state(mesh, self.params_nonblock, self.specs_nonblock)
        pblk = shard_state(mesh, self.params_blocks, self.specs_blocks)
        opt_nb = self.opt.init(pnb)
        opt_blk = self.opt.init(pblk)
        shard_deg = self.hcg.get_sharding_parallel_world_size()
        if self.zero >= 1 and shard_deg > 1:
            slot_nb = self.slot_specs_nb
            slot_blk = self.slot_specs_blk
        else:
            slot_nb = self.specs_nonblock
            slot_blk = self.specs_blocks
        def lay_opt(ostate, pspecs):
            return {
                "step": ostate["step"],
                "slots": {k: shard_state(mesh, v, pspecs[k])
                          for k, v in ostate["slots"].items()},
                "master": {k: (None if v is None else
                               shard_state(mesh, v, pspecs[k]))
                           for k, v in ostate["master"].items()},
            }
        opt_nb = lay_opt(opt_nb, slot_nb)
        opt_blk = lay_opt(opt_blk, slot_blk)
        return pnb, pblk, opt_nb, opt_blk

    # ---- functional model pieces (non-block params used directly) ------
    def _take_table(self, pnb, key, idx):
        """Row lookup honoring the table's row sharding: row-sharded
        tables go through the GSPMD gather with an f32 scatter-
        accumulate bwd (_take_rows_f32grad) — a plain bf16 take's
        scatter-add bwd CHECK-crashes XLA in bf16 pp>1 hybrids, and the
        manual masked-lookup alternative (sharded_row_take) trips a psum
        replica-group CHECK on hybrid meshes (round-5 notes)."""
        spec = (self.specs_nonblock.get(key) or P())
        row_axes = tuple(spec)[0] if tuple(spec) else None
        if row_axes is None:
            return jnp.take(pnb[key], idx.astype(jnp.int32), axis=0)
        from ..distributed.meta_parallel.mp_layers import _take_rows_f32grad
        return _take_rows_f32grad(pnb[key], idx)

    def _embed(self, pnb, ids):
        cfg = self.cfg
        pos = jnp.arange(ids.shape[1])[None, :]
        x = self._take_table(pnb, self.KEY_WTE, ids) + \
            self._take_table(pnb, self.KEY_WPE, pos)
        # context parallel: activations ride the sep axis on the seq dim
        seq_axis = "sep" if getattr(cfg, "cp", False) else None
        return _maybe_constraint(x, P(None, seq_axis, None))

    def _final(self, pnb, x):
        cfg = self.cfg
        w = pnb.get(self.KEY_LNF_W)
        b = pnb.get(self.KEY_LNF_B)
        x = F.layer_norm(x, cfg.hidden_size, w, b, cfg.layer_norm_eps)
        # tied head: second use of the wte table (grads from both uses are
        # summed by AD — SharedLayerDesc semantics); logits stay sharded on
        # vocab over mp AND pp so no stage materializes the full [b,s,V]
        logits = jnp.einsum("bsh,vh->bsv", x, pnb[self.KEY_WTE])
        return _maybe_constraint(logits, P(None, None, self._vocab_axes))

    def _block_apply(self, blk_params, x):
        out, _ = functional_call(self.template_block, blk_params, {}, (x,),
                                 train=True)
        return out

    def _body(self, pblk_local, x):
        """Apply this stage's K blocks via scan (K = L/S local slice)."""
        def one(carry, bp):
            return self._block_apply(bp, carry), None
        out, _ = jax.lax.scan(one, x, pblk_local)
        return out

    # ---- pipeline carry hooks (overridden by GPTMoEHybridTrainer to
    # thread the gate aux loss through the schedule) --------------------
    def _pack_microbatches(self, mb):
        """[M, mb, s, h] hidden -> (activation pytree, x_spec pytree)."""
        seq_axis = "sep" if getattr(self.cfg, "cp", False) else None
        return mb, P(None, self.batch_spec()[0], seq_axis, None)

    def _pipeline_manual_axes(self):
        """Extra manual axes the pipeline shard_map must bind: the stage
        body runs ring/Ulysses collectives over sep when context
        parallelism is on (nested shard_map under pp is illegal)."""
        if getattr(self.cfg, "cp", False) and \
                self.hcg.get_sep_parallel_world_size() > 1:
            return frozenset({"sep"})
        return frozenset()

    def _unpack_pipeline_output(self, out):
        """activation pytree -> ([M, mb, s, h] hidden, extra loss term)."""
        return out, 0.0

    def _serial_forward(self, pblk, x):
        """S == 1 path: scan all blocks; -> (hidden, extra loss term)."""
        body = _remat_wrap(self._block_apply, self.cfg.remat)

        def one(carry, bp):
            return body(bp, carry), None
        x, _ = jax.lax.scan(one, x, pblk)
        return x, 0.0

    # ------------------------------------------------------------------
    def loss_fn(self, pnb, pblk, ids, labels):
        cfg = self.cfg
        x = self._embed(pnb, ids)
        if self.S > 1:
            b, s, h = x.shape
            M = self.M
            mb, x_spec = self._pack_microbatches(x.reshape(M, b // M, s, h))
            if self.V > 1:
                from ..distributed.pipelining import \
                    pipeline_apply_interleaved
                out = pipeline_apply_interleaved(
                    self._body, pblk, mb, self.mesh, self.S, self.V,
                    remat=cfg.remat, x_spec=x_spec,
                    param_inner_specs=self.specs_blocks,
                    extra_manual_axes=self._pipeline_manual_axes())
            else:
                out = pipeline_apply(self._body, pblk, mb, self.mesh, self.S,
                                     remat=cfg.remat, x_spec=x_spec,
                                     param_inner_specs=self.specs_blocks,
                                     extra_manual_axes=self._pipeline_manual_axes())
            hidden, extra = self._unpack_pipeline_output(out)
            x = hidden.reshape(b, s, h)
        else:
            x, extra = self._serial_forward(pblk, x)
        logits = self._final(pnb, x)
        per_tok = parallel_cross_entropy(logits, labels,
                                         mp_axis=self._vocab_axes)
        return jnp.mean(per_tok) + extra

    def build_step(self):
        opt = self.opt
        zero2 = (self.zero >= 2 and
                 self.hcg.get_sharding_parallel_world_size() > 1)

        def step(pnb, pblk, opt_nb, opt_blk, ids, labels, lr):
            loss, (g_nb, g_blk) = jax.value_and_grad(
                self.loss_fn, argnums=(0, 1))(pnb, pblk, ids, labels)
            if zero2:
                # stage 2: materialize grads SHARDED — XLA turns the dp/
                # sharding grad all-reduce into reduce-scatter + the update
                # math runs on 1/degree of each tensor
                g_nb = {k: _maybe_constraint(v, self.slot_specs_nb[k])
                        for k, v in g_nb.items()}
                g_blk = {k: _maybe_constraint(v, self.slot_specs_blk[k])
                         for k, v in g_blk.items()}
            new_nb, opt_nb = opt.update(g_nb, opt_nb, pnb, lr=lr)
            new_blk, opt_blk = opt.update(g_blk, opt_blk, pblk, lr=lr)
            if zero2 and self.zero < 3:
                # params stay unsharded in stages 1/2: bring the updated
                # values back to their declared layout
                new_nb = {k: _maybe_constraint(v, self.specs_nonblock[k])
                          for k, v in new_nb.items()}
                new_blk = {k: _maybe_constraint(v, self.specs_blocks[k])
                           for k, v in new_blk.items()}
            return new_nb, new_blk, opt_nb, opt_blk, loss

        return step

    def jit_step(self, donate: bool = True):
        if self._jit_step is None:
            step = self.build_step()
            self._jit_step = jax.jit(
                step, donate_argnums=(0, 1, 2, 3) if donate else ())
        return self._jit_step

    # ------------------------------------------------------------------
    def make_batch(self, batch: int, seq: Optional[int] = None, seed: int = 0):
        seq = seq or self.cfg.max_seq_len
        rng = np.random.RandomState(seed)
        ids = rng.randint(0, self.cfg.vocab_size, (batch, seq + 1))
        # keep the batch on host: put_global ingests numpy directly
        # (jnp.asarray first would bounce host->device->host on the
        # multi-controller path)
        x = np.ascontiguousarray(ids[:, :-1])
        y = np.ascontiguousarray(ids[:, 1:])
        seq_axis = "sep" if getattr(self.cfg, "cp", False) else None
        from ..distributed.sharding_utils import put_global
        bs = NamedSharding(self.mesh, P(self.batch_spec()[0], seq_axis))
        return put_global(x, bs), put_global(y, bs)

    def train_step(self, state_tuple, ids, labels):
        pnb, pblk, onb, oblk = state_tuple
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        pnb, pblk, onb, oblk, loss = self.jit_step()(
            pnb, pblk, onb, oblk, ids, labels, lr)
        return (pnb, pblk, onb, oblk), loss


class GPTMoEHybridTrainer(GPTHybridTrainer):
    """Hybrid-parallel GPT-MoE trainer: dp x pp x ZeRO x EP in ONE jitted
    step (reference: paddle.incubate.distributed.models.moe GPT over the
    fleet expert group, composed with PipelineParallel /
    DygraphShardingOptimizer — SURVEY.md §2.3 EP + Hybrid rows).

    Experts shard over the first-class ``ep`` mesh axis (MoELayer defaults
    its group to HCG.get_expert_parallel_group() when ep_degree > 1), so
    expert dispatch einsums compile to all-to-all over ep while blocks
    pipeline over pp and the batch shards over dp/sharding.

    Blocks must be uniform (``cfg.moe_every == 1``) — the fused pipeline
    schedule's requirement, same as the reference PipelineLayer uniform
    segmentation.

    The gate load-balance aux losses ride the pipeline INSIDE the
    activation pytree ({"h": hidden, "aux": scalar}): each stage adds its
    blocks' aux terms as the microbatch flows through, and the last stage
    emits the per-microbatch totals — the one-program SPMD form of the
    reference's cross-stage aux-loss reduction.  With microbatches > 1 the
    batch aux is the mean of per-microbatch aux values (a documented,
    standard estimator deviation: the balance loss is nonlinear in the
    token set; with M=1 it equals the serial value exactly).
    """

    BLOCK_PREFIX = "h."
    KEY_WTE = "wte.weight"
    KEY_WPE = "wpe.weight"
    KEY_LNF_W = "ln_f.weight"
    KEY_LNF_B = "ln_f.bias"

    def __init__(self, cfg, hcg, optimizer, microbatches: int = 1,
                 zero_stage: int = 1, vpp: int = 1):
        if cfg.moe_every != 1:
            raise ValueError(
                "GPTMoEHybridTrainer needs uniform blocks: set "
                "cfg.moe_every = 1 (every block MoE) — the fused pipeline "
                "schedule requires structurally identical stages, like the "
                "reference PipelineLayer's uniform segmentation")
        # ep x mp composition: with a model-parallel degree in the fleet
        # config, experts default to internal tensor parallelism over the
        # mp axis (reference: the fleet call site passes
        # hcg.get_model_parallel_group() into MoELayer(mp_group))
        if cfg.mp_group is None and hcg.get_model_parallel_world_size() > 1:
            cfg.mp_group = "mp"
        super().__init__(cfg, hcg, optimizer, microbatches=microbatches,
                         zero_stage=zero_stage, vpp=vpp)

    def _make_model(self, cfg):
        from .gpt_moe import GPTMoEForCausalLM
        return GPTMoEForCausalLM(cfg)

    def _get_template_block(self):
        return self.model.h[0]

    # ---- MoE stage body: hidden + aux accumulator --------------------
    def _block_apply(self, blk_params, x):
        out, nb = functional_call(self.template_block, blk_params, None,
                                  (x,), train=True)
        aux = jnp.zeros((), jnp.float32)
        for k, v in nb.items():
            if k.endswith("aux_loss"):
                aux = aux + v
        return out, aux

    def _body(self, pblk_local, carry):
        def one(c, bp):
            out, aux_inc = self._block_apply(bp, c["h"])
            return {"h": out, "aux": c["aux"] + aux_inc}, None
        out, _ = jax.lax.scan(one, carry, pblk_local)
        return out

    def _pack_microbatches(self, mb):
        M = mb.shape[0]
        return ({"h": mb, "aux": jnp.zeros((M,), jnp.float32)},
                {"h": P(None, self.batch_spec()[0]), "aux": None})

    def _unpack_pipeline_output(self, out):
        return out["h"], self.cfg.aux_weight * jnp.mean(out["aux"])

    def _serial_forward(self, pblk, x):
        # per-block remat inside the scan — same granularity as the base
        # class (one recompute chunk per block, not one for all L blocks)
        blk = _remat_wrap(self._block_apply, self.cfg.remat)

        def one(c, bp):
            out, aux_inc = blk(bp, c["h"])
            return {"h": out, "aux": c["aux"] + aux_inc}, None

        carry, _ = jax.lax.scan(
            one, {"h": x, "aux": jnp.zeros((), jnp.float32)}, pblk)
        return carry["h"], self.cfg.aux_weight * carry["aux"]
