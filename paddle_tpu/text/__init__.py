"""paddle.text surface (reference: python/paddle/text/ — dataset
downloaders: Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14,
WMT16, ViterbiDecoder).

This environment is zero-egress (no downloads), so the dataset classes
parse the reference archive formats from explicit local paths (see datasets.py); the
ViterbiDecoder — the one compute component — is implemented natively
(lax.scan dynamic program)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..nn.layer import Layer

__all__ = ["ViterbiDecoder", "viterbi_decode", "Conll05st", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


def viterbi_decode(potentials, transitions, lengths=None,
                   include_bos_eos_tag: bool = True, name=None):
    """Reference: paddle.text.viterbi_decode — best tag path under a CRF.

    potentials [B, T, N]; transitions [N, N] with the SAME tag dimension
    as the emissions.  With ``include_bos_eos_tag`` the reference treats
    the LAST row as the start (BOS->tag) scores and the SECOND-TO-LAST
    column as the stop (tag->EOS) scores (paddle convention:
    start_idx = -1, stop_idx = -2).  Returns (scores [B], paths [B, T]).
    """
    potentials = jnp.asarray(potentials, jnp.float32)
    B, T, N = potentials.shape
    trans = jnp.asarray(transitions, jnp.float32)
    if trans.shape != (N, N):
        raise ValueError(f"transitions must be [{N}, {N}] to match the "
                         f"emission tag dim, got {trans.shape}")
    if include_bos_eos_tag:
        bos = trans[-1, :]          # start_idx = -1 (last row)
        eos = trans[:, -2]          # stop_idx  = -2 (second-to-last col)
    else:
        bos = jnp.zeros((N,), jnp.float32)
        eos = jnp.zeros((N,), jnp.float32)
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)

    alpha0 = bos[None, :] + potentials[:, 0]             # [B, N]

    def step(alpha, t):
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, t, j]
        scores = alpha[:, :, None] + trans[None, :, :] + \
            potentials[:, t][:, None, :]
        best_prev = jnp.argmax(scores, axis=1)           # [B, N]
        new_alpha = jnp.max(scores, axis=1)
        # sequences already finished keep their alpha (mask by length)
        keep = (t < lengths)[:, None]
        new_alpha = jnp.where(keep, new_alpha, alpha)
        return new_alpha, best_prev

    alpha, backptrs = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    # backptrs [T-1, B, N]
    final = alpha + eos[None, :]
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1)                # [B]

    def backtrack(carry, t):
        tag = carry
        bp = backptrs[t]                                 # [B, N]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # positions beyond a sequence's length keep the same tag
        prev = jnp.where(t + 1 < lengths, prev, tag)
        return prev, tag

    first, tags_rev = jax.lax.scan(backtrack, last_tag,
                                   jnp.arange(T - 2, -1, -1))
    paths = jnp.concatenate([first[None], jnp.flip(tags_rev, 0)], axis=0)
    return scores, jnp.moveaxis(paths, 0, 1)


class ViterbiDecoder(Layer):
    """Layer form (reference: paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.register_buffer("transitions", jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)
