"""paddle.text datasets — local-file parsers for the reference formats.

Reference: python/paddle/text/datasets/ — uci_housing.py, imdb.py,
imikolov.py, movielens.py, wmt14.py, wmt16.py, conll05.py (SURVEY.md §2.2
"Python front end").  The reference downloads archives; this environment
is zero-egress, so every dataset takes explicit local paths to the SAME
file formats the reference archives contain (the vision.datasets stance)
and raises a guidance error when absent.  Parsing/semantics follow the
reference: UCIHousing's (x-avg)/(max-min) normalization and 80/20 split,
Imdb's pos=0/neg=1 labels and frequency-sorted vocab, Imikolov's NGRAM/
SEQ modes with <s>/<e>/<unk>, Movielens' ::-separated ml-1m tables with
multi-hot categories, WMT's <s>/<e>/<unk>-framed id pairs, Conll05st's
props-to-BIO conversion.
"""

from __future__ import annotations

import os
import re
import tarfile
from collections import Counter
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT14", "WMT16",
           "Conll05st"]


def _need(path, name, what):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"paddle_tpu.text.{name}: no network access in this "
            f"environment — provide {what} as a local file (same format "
            f"as the reference archive)")


class UCIHousing(Dataset):
    """Reference: uci_housing.py — 13 features + MEDV target, whitespace
    table; features normalized by (x - avg) / (max - min) over the WHOLE
    table, first 80% train / rest test."""

    def __init__(self, data_file=None, mode="train", download=True):
        _need(data_file, "UCIHousing", "data_file (housing.data)")
        raw = np.loadtxt(data_file).astype(np.float32)
        if raw.ndim == 1:
            raw = raw[None, :]
        feats, target = raw[:, :-1], raw[:, -1:]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        span = np.where(mx - mn == 0, 1.0, mx - mn)
        feats = (feats - avg) / span
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = feats[:split]
            self.label = target[:split]
        else:
            self.data = feats[split:]
            self.label = target[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]


_TOKEN_RE = re.compile(r"[a-z0-9']+")


class Imdb(Dataset):
    """Reference: imdb.py — aclImdb tar: {train,test}/{pos,neg}/*.txt.
    ONE vocab built from train AND test (reference build_dict pattern
    matches both splits) keeping words with frequency > cutoff,
    frequency-sorted (ties lexicographic); pos label 0, neg label 1."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        _need(data_file, "Imdb", "data_file (aclImdb tar.gz)")
        if mode not in ("train", "test"):
            raise ValueError(f"mode must be 'train' or 'test', got {mode!r}")
        any_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        docs_raw: List[Tuple[str, List[str]]] = []
        vocab_counter: Counter = Counter()
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                g = any_pat.match(m.name.lstrip("./"))
                if not g:
                    continue
                text = tf.extractfile(m).read().decode("utf-8", "ignore")
                toks = _TOKEN_RE.findall(text.lower())
                # vocab sees BOTH splits (reference: one shared dict)
                vocab_counter.update(toks)
                if g.group(1) == mode:
                    docs_raw.append((g.group(2), toks))
        # words with freq > cutoff, frequency-sorted (reference build_dict)
        items = [(w, c) for w, c in vocab_counter.items() if c > cutoff]
        items.sort(key=lambda wc: (-wc[1], wc[0]))
        self.word_idx = {w: i for i, (w, c) in enumerate(items)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(t, unk) for t in toks],
                                np.int64) for _, toks in docs_raw]
        self.labels = [np.int64(0 if pol == "pos" else 1)
                       for pol, _ in docs_raw]

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(Dataset):
    """Reference: imikolov.py — PTB: simple-examples/data/ptb.{train,valid}
    .txt; NGRAM windows framed by <s>/<e> or SEQ id lists; vocab by
    min-word-freq, '<unk>' mapped from PTB's own token."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        _need(data_file, "Imikolov", "data_file (simple-examples tar.gz)")
        if data_type.upper() == "NGRAM" and window_size <= 0:
            raise ValueError(
                "Imikolov NGRAM mode needs window_size > 0 (the reference "
                "default window_size=-1 is only valid for data_type='SEQ')")
        split = "train" if mode == "train" else "valid"

        with tarfile.open(data_file) as tf:
            members = {m.name.lstrip("./"): m for m in tf.getmembers()}

            def read(which):
                name = f"simple-examples/data/ptb.{which}.txt"
                member = members.get(name)
                if member is None:
                    raise RuntimeError(f"{name} not in archive")
                return tf.extractfile(member).read().decode().splitlines()

            # the vocab ALWAYS comes from the train split (reference:
            # build_dict reads ptb.train.txt) so train/valid ids align
            train_lines = read("train")
            lines = train_lines if split == "train" else read(split)
        counter: Counter = Counter()
        for ln in train_lines:
            counter.update(ln.split())
        counter.pop("<unk>", None)
        words = [w for w, c in counter.items() if c >= min_word_freq]
        words.sort(key=lambda w: (-counter[w], w))
        self.word_idx = {w: i for i, w in enumerate(words)}
        self.word_idx["<unk>"] = len(self.word_idx)
        self.word_idx.setdefault("<s>", len(self.word_idx))
        self.word_idx.setdefault("<e>", len(self.word_idx))
        unk, s, e = (self.word_idx["<unk>"], self.word_idx["<s>"],
                     self.word_idx["<e>"])
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln.split()]
            if data_type.upper() == "NGRAM":
                seq = [s] + ids + [e]
                if len(seq) < window_size:
                    continue
                for i in range(window_size, len(seq) + 1):
                    self.data.append(
                        np.asarray(seq[i - window_size:i], np.int64))
            elif data_type.upper() == "SEQ":
                self.data.append(np.asarray([s] + ids + [e], np.int64))
            else:
                raise ValueError("data_type must be NGRAM or SEQ")

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class Movielens(Dataset):
    """Reference: movielens.py — ml-1m: users.dat/movies.dat/ratings.dat,
    '::'-separated; item = (user_id, gender, age, job, mov_id,
    multi-hot categories, title ids, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        _need(data_file, "Movielens", "data_file (ml-1m archive dir or zip)")
        import zipfile

        def read(name):
            if os.path.isdir(data_file):
                with open(os.path.join(data_file, name), "rb") as f:
                    return f.read().decode("latin1")
            with zipfile.ZipFile(data_file) as z:
                inner = next((n for n in z.namelist() if n.endswith(name)),
                             None)
                if inner is None:
                    raise RuntimeError(
                        f"paddle_tpu.text.Movielens: {name} not found in "
                        f"{data_file} (expected the ml-1m layout)")
                return z.read(inner).decode("latin1")

        users = {}
        for ln in read("users.dat").splitlines():
            uid, gender, age, job, _zip = ln.split("::")
            users[int(uid)] = (np.int64(int(uid)),
                               np.int64(0 if gender == "M" else 1),
                               np.int64(int(age)), np.int64(int(job)))
        categories, titles_vocab = {}, {}
        movies = {}
        for ln in read("movies.dat").splitlines():
            mid, title, cats = ln.split("::")
            for c in cats.split("|"):
                categories.setdefault(c, len(categories))
            for w in _TOKEN_RE.findall(title.lower()):
                titles_vocab.setdefault(w, len(titles_vocab))
            movies[int(mid)] = (title, cats.split("|"))
        self.categories_dict = categories
        self.movie_title_dict = titles_vocab
        rows = []
        rng = np.random.RandomState(rand_seed)
        for ln in read("ratings.dat").splitlines():
            uid, mid, rating, _ts = ln.split("::")
            uid, mid = int(uid), int(mid)
            if uid not in users or mid not in movies:
                continue
            is_test = rng.rand() < test_ratio
            if (mode == "test") != is_test:
                continue
            title, cats = movies[mid]
            cat_vec = np.zeros(len(categories), np.int64)
            for c in cats:
                cat_vec[categories[c]] = 1
            title_ids = np.asarray(
                [titles_vocab[w] for w in _TOKEN_RE.findall(title.lower())],
                np.int64)
            rows.append((*users[uid], np.int64(mid), cat_vec, title_ids,
                         np.float32(float(rating))))
        self.rows = rows

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, idx):
        return self.rows[idx]


class _WMTBase(Dataset):
    _NAME = "WMT"

    def __init__(self, data_file, mode, src_dict_size, trg_dict_size, lang):
        _need(data_file, self._NAME, "data_file (parallel-corpus tar.gz)")
        self._src_lang = lang          # None for WMT14 (unlabeled sides)
        pairs = self._read_pairs(data_file, mode, lang)
        src_c: Counter = Counter()
        trg_c: Counter = Counter()
        for s, t in pairs:
            src_c.update(s)
            trg_c.update(t)
        self.src_ids = self._dict(src_c, src_dict_size)
        self.trg_ids = self._dict(trg_c, trg_dict_size)
        s_unk, t_unk = self.src_ids["<unk>"], self.trg_ids["<unk>"]
        s_, e_ = self.trg_ids["<s>"], self.trg_ids["<e>"]
        self.data = []
        for s, t in pairs:
            sid = np.asarray([self.src_ids.get(w, s_unk) for w in s],
                             np.int64)
            tid = [self.trg_ids.get(w, t_unk) for w in t]
            self.data.append((sid,
                              np.asarray([s_] + tid, np.int64),
                              np.asarray(tid + [e_], np.int64)))

    @staticmethod
    def _dict(counter, size):
        words = sorted(counter, key=lambda w: (-counter[w], w))
        d = {"<s>": 0, "<e>": 1, "<unk>": 2}
        for w in words[:max(size - 3, 0)]:
            if w not in d:
                d[w] = len(d)
        return d

    def _read_pairs(self, data_file, mode, lang):
        raise NotImplementedError

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]

    def get_dict(self, lang="src", reverse=False):
        """Reference surface: src/trg dicts (optionally id->word).  A
        bare boolean positional is the reference's reverse flag for the
        SOURCE dict (wmt14.get_dict(reverse)).  Language names resolve
        against the dataset's OWN source side (WMT16(lang='de') makes
        'de' the source dict)."""
        if isinstance(lang, bool):
            lang, reverse = "src", lang
        if self._src_lang is not None and lang not in ("src", "source",
                                                       "trg", "target"):
            other = "de" if self._src_lang == "en" else "en"
            if lang not in (self._src_lang, other):
                raise ValueError(
                    f"unknown dict language {lang!r}; this dataset has "
                    f"source={self._src_lang!r}, target={other!r} (or use "
                    "'src'/'trg')")
            src = lang == self._src_lang
        else:
            src = lang in ("en", "source", "src")
        d = self.src_ids if src else self.trg_ids
        if reverse:
            return {i: w for w, i in d.items()}
        return d


class WMT14(_WMTBase):
    """Reference: wmt14.py — members {train,test,gen}/... with
    'src seq\\ttrg seq' lines."""

    _NAME = "WMT14"

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        if mode not in ("train", "test", "gen"):
            raise ValueError(
                f"mode must be train/test/gen, got {mode!r}")
        super().__init__(data_file, mode, dict_size, dict_size, None)

    def _read_pairs(self, data_file, mode, lang):
        pairs = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if not m.isfile() or f"{mode}/" not in m.name:
                    continue
                for ln in tf.extractfile(m).read().decode(
                        "utf-8", "ignore").splitlines():
                    if "\t" not in ln:
                        continue
                    s, t = ln.split("\t", 1)
                    pairs.append((s.split(), t.split()))
        return pairs


class WMT16(_WMTBase):
    """Reference: wmt16.py — {train,val,test}.{en,de} parallel files;
    lang selects which side is source."""

    _NAME = "WMT16"

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        super().__init__(data_file, mode, src_dict_size, trg_dict_size, lang)

    def _read_pairs(self, data_file, mode, lang):
        splits = {"train": "train", "test": "test", "val": "val",
                  "dev": "val"}
        if mode not in splits:
            raise ValueError(
                f"mode must be one of {sorted(splits)}, got {mode!r}")
        split = splits[mode]
        other = "de" if lang == "en" else "en"
        with tarfile.open(data_file) as tf:
            def read(suffix):
                member = next((m for m in tf.getmembers()
                               if m.name.endswith(f"{split}.{suffix}")), None)
                if member is None:
                    raise RuntimeError(f"{split}.{suffix} not in archive")
                return tf.extractfile(member).read().decode(
                    "utf-8", "ignore").splitlines()
            src_lines, trg_lines = read(lang), read(other)
        if len(src_lines) != len(trg_lines):
            raise RuntimeError(
                f"parallel corpus misaligned: {len(src_lines)} {lang} lines"
                f" vs {len(trg_lines)} {other} lines")
        return [(s.split(), t.split())
                for s, t in zip(src_lines, trg_lines)]


class Conll05st(Dataset):
    """Reference: conll05.py — SRL: a words file (one token per line,
    blank line between sentences) + a props file (predicate column +
    per-predicate span columns like '(A0*', '*)', '(V*)'); spans convert
    to BIO tags; one sample per (sentence, predicate)."""

    def __init__(self, words_file=None, props_file=None, mode="train",
                 download=True, **kw):
        _need(words_file, "Conll05st", "words_file")
        _need(props_file, "Conll05st", "props_file")
        sentences = self._blocks(words_file)
        props = self._blocks(props_file)
        if len(sentences) != len(props):
            raise ValueError("words/props sentence counts differ")
        self.word_dict, self.label_dict = {}, {"O": 0}
        samples = []
        for words, prop in zip(sentences, props):
            words = [w.split()[0] for w in words]
            for w in words:
                self.word_dict.setdefault(w.lower(), len(self.word_dict))
            cols = [ln.split() for ln in prop]
            n_pred = len(cols[0]) - 1
            if any(len(c) != len(cols[0]) for c in cols):
                raise ValueError(
                    f"ragged props block (sentence starting {words[0]!r}): "
                    f"rows have differing column counts")
            for p in range(1, n_pred + 1):
                tags = self._spans_to_bio([c[p] for c in cols])
                for t in tags:
                    self.label_dict.setdefault(t, len(self.label_dict))
                pred_idx = next((i for i, c in enumerate(cols)
                                 if c[p].startswith("(V")), None)
                if pred_idx is None:
                    raise ValueError(
                        f"props column {p} has no (V* predicate span "
                        f"(sentence starting {words[0]!r})")
                samples.append((
                    np.asarray([self.word_dict[w.lower()] for w in words],
                               np.int64),
                    np.int64(self.word_dict[words[pred_idx].lower()]),
                    np.asarray([self.label_dict[t] for t in tags], np.int64)))
        self.samples = samples

    @staticmethod
    def _blocks(path):
        blocks, cur = [], []
        with open(path) as f:
            for ln in f:
                ln = ln.rstrip("\n")
                if ln.strip():
                    cur.append(ln)
                elif cur:
                    blocks.append(cur)
                    cur = []
        if cur:
            blocks.append(cur)
        return blocks

    @staticmethod
    def _spans_to_bio(col: Sequence[str]) -> List[str]:
        tags, label = [], None
        for cell in col:
            cell = cell.strip()
            m = re.match(r"\(([^*()]+)\*", cell)
            if m:
                label = m.group(1)
                tags.append(f"B-{label}")
            elif label is not None:
                tags.append(f"I-{label}")
            else:
                tags.append("O")
            if ")" in cell:
                label = None
        return tags

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        return self.samples[idx]
