"""paddle.audio.datasets — TESS and ESC50 over local files.

Reference: python/paddle/audio/datasets/ — tess.py (emotion folders of
OAF_word_emotion.wav files, seeded split), esc50.py (audio/*.wav +
meta/esc50.csv, fold-based split); both yield (feature|waveform, label)
with feature_type 'raw' | 'mfcc' | 'spectrogram' | 'melspectrogram' |
'logmelspectrogram' computed by paddle.audio.features (SURVEY.md §2.2).
Zero-egress stance: explicit local paths to the extracted archive layout,
guidance error when absent (the vision/text datasets pattern).  WAV
reading is stdlib `wave` (PCM16/PCM8), which the reference archives use.
"""

from __future__ import annotations

import os
import wave
from typing import List, Optional, Tuple

import numpy as np

from ..io import Dataset

__all__ = ["TESS", "ESC50", "load_wav"]


def _need(path, name, what):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"paddle_tpu.audio.{name}: no network access in this "
            f"environment — provide {what} (extracted archive layout)")


def load_wav(path: str) -> Tuple[np.ndarray, int]:
    """(waveform float32 [-1, 1] mono, sample_rate) from a PCM wav."""
    with wave.open(path, "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        width = w.getsampwidth()
        ch = w.getnchannels()
        raw = w.readframes(n)
    if width == 2:
        x = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 1:
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported sample width {width}")
    if ch > 1:
        x = x.reshape(-1, ch).mean(1)
    return x, sr


class _AudioBase(Dataset):
    _FEATS = ("raw", "spectrogram", "melspectrogram", "logmelspectrogram",
              "mfcc")

    def __init__(self, feature_type: str, archive_dir: str, **feat_kw):
        if feature_type not in self._FEATS:
            raise ValueError(
                f"feature_type must be one of {self._FEATS}")
        self.feature_type = feature_type
        self._feat_kw = feat_kw
        self._extractors = {}
        self._files: List[str] = []
        self._labels: List[int] = []

    def _extract(self, waveform: np.ndarray, sr: int):
        if self.feature_type == "raw":
            return waveform
        import jax.numpy as jnp
        x = jnp.asarray(waveform)[None, :]
        return np.asarray(self._extractor(sr)(x)[0])

    def _extractor(self, sr: int):
        """One feature layer per sample rate (the fbank/DCT matrices and
        the layer's jit identity are reused across __getitem__ calls)."""
        layer = self._extractors.get(sr)
        if layer is None:
            from . import features as AF
            cls = {"spectrogram": AF.Spectrogram,
                   "melspectrogram": AF.MelSpectrogram,
                   "logmelspectrogram": AF.LogMelSpectrogram,
                   "mfcc": AF.MFCC}[self.feature_type]
            kw = dict(self._feat_kw)
            if self.feature_type != "spectrogram":
                kw.setdefault("sr", sr)
            layer = cls(**kw)
            self._extractors[sr] = layer
        return layer

    def __len__(self):
        return len(self._files)

    def __getitem__(self, idx):
        wav, sr = load_wav(self._files[idx])
        return self._extract(wav, sr), np.int64(self._labels[idx])


class TESS(_AudioBase):
    """Reference: tess.py — TESS emotional speech: files named
    <speaker>_<word>_<emotion>.wav; label = emotion index over the sorted
    emotion set; seeded shuffle then n_folds split (mode train = all but
    the held-out fold, dev = the fold)."""

    def __init__(self, mode: str = "train", n_folds: int = 5, split: int = 1,
                 feature_type: str = "raw", archive_dir: Optional[str] = None,
                 seed: int = 0, **feat_kw):
        super().__init__(feature_type, archive_dir, **feat_kw)
        if mode not in ("train", "dev"):
            raise ValueError(f"mode must be 'train' or 'dev', got {mode!r}")
        _need(archive_dir, "TESS", "archive_dir (folder of emotion wavs)")
        if not 1 <= split <= n_folds:
            raise ValueError(f"split must be in [1, {n_folds}]")
        files = []
        for root, _dirs, names in os.walk(archive_dir):
            for nm in sorted(names):
                if nm.lower().endswith(".wav"):
                    files.append(os.path.join(root, nm))
        files.sort()
        emotions = sorted({os.path.splitext(os.path.basename(f))[0]
                           .rsplit("_", 1)[-1].lower() for f in files})
        self.emotions = emotions
        lab = {e: i for i, e in enumerate(emotions)}
        rng = np.random.RandomState(seed)
        order = rng.permutation(len(files))
        fold = np.arange(len(files)) % n_folds + 1  # over the shuffled order
        keep = (fold != split) if mode == "train" else (fold == split)
        for pos, take in zip(order, keep):
            if take:
                f = files[pos]
                self._files.append(f)
                self._labels.append(
                    lab[os.path.splitext(os.path.basename(f))[0]
                        .rsplit("_", 1)[-1].lower()])


class ESC50(_AudioBase):
    """Reference: esc50.py — audio/*.wav + meta/esc50.csv
    (filename,fold,target,...); mode train = folds != split, dev = fold
    == split."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feature_type: str = "raw", archive_dir: Optional[str] = None,
                 **feat_kw):
        super().__init__(feature_type, archive_dir, **feat_kw)
        if mode not in ("train", "dev"):
            raise ValueError(f"mode must be 'train' or 'dev', got {mode!r}")
        if not 1 <= split <= 5:
            raise ValueError(f"split must be in [1, 5], got {split}")
        _need(archive_dir, "ESC50", "archive_dir (audio/ + meta/esc50.csv)")
        meta = os.path.join(archive_dir, "meta", "esc50.csv")
        _need(meta, "ESC50", "meta/esc50.csv")
        with open(meta) as f:
            header = f.readline().strip().split(",")
            fn_i = header.index("filename")
            fold_i = header.index("fold")
            tgt_i = header.index("target")
            for ln in f:
                cells = ln.strip().split(",")
                if not cells or len(cells) <= max(fn_i, fold_i, tgt_i):
                    continue
                fold = int(cells[fold_i])
                if (mode == "train") == (fold != split):
                    self._files.append(
                        os.path.join(archive_dir, "audio", cells[fn_i]))
                    self._labels.append(int(cells[tgt_i]))
