"""audio.features layers (reference: python/paddle/audio/features/layers.py
— Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..nn.layer import Layer
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length: int, hop_length: int, center: bool,
           pad_mode: str = "reflect"):
    """x [..., T] -> frames [..., n_frames, frame_length]."""
    if center:
        pad = frame_length // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    T = x.shape[-1]
    n_frames = 1 + (T - frame_length) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return x[..., idx]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype=None):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        win = AF.get_window(window, self.win_length)
        if dtype is not None:
            win = win.astype(dtype)
        self.register_buffer("window", win)

    def forward(self, x):
        """x [..., T] -> [..., n_fft//2+1, n_frames] (reference layout)."""
        frames = _frame(x, self.win_length, self.hop_length, self.center,
                        self.pad_mode)
        frames = frames * self.window
        # rfft's n= zero-pads win_length -> n_fft itself
        spec = jnp.fft.rfft(frames, n=self.n_fft, axis=-1)
        mag = jnp.abs(spec) ** self.power
        return jnp.swapaxes(mag, -1, -2)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: str = "slaney", dtype=None):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype=dtype)
        fb = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk,
                                     norm)
        if dtype is not None:
            fb = fb.astype(dtype)
        self.register_buffer("fbank", fb)

    def forward(self, x):
        spec = self.spectrogram(x)             # [..., bins, frames]
        return jnp.einsum("mb,...bt->...mt", self.fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, ref_value: float = 1.0,
                 amin: float = 1e-10, top_db: Optional[float] = None,
                 **mel_kw):
        super().__init__()
        self.mel = MelSpectrogram(sr=sr, **mel_kw)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, **mel_kw):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, **mel_kw)
        n_mels = self.log_mel.mel.fbank.shape[0]
        self.register_buffer("dct", AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        lm = self.log_mel(x)                    # [..., mels, frames]
        return jnp.einsum("mk,...mt->...kt", self.dct, lm)
