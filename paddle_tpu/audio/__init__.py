"""paddle.audio parity — signal features (Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC).

Reference: python/paddle/audio/features/layers.py + functional/ (window +
mel filterbank math on the framework's fft ops).

TPU-native: framing is a gather, the STFT is jnp.fft over frames, mel
banks are one [n_mels, n_bins] matmul — everything jits.  Datasets
(paddle.audio.datasets TESS/ESC50) parse the extracted reference archive
layouts from explicit LOCAL paths (zero-egress stance; see datasets.py).
"""

from . import features  # noqa: F401
from . import functional  # noqa: F401
from . import datasets  # noqa: F401

__all__ = ["features", "functional", "datasets"]
