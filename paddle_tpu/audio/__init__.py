"""paddle.audio parity — signal features (Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC).

Reference: python/paddle/audio/features/layers.py + functional/ (window +
mel filterbank math on the framework's fft ops).

TPU-native: framing is a gather, the STFT is jnp.fft over frames, mel
banks are one [n_mels, n_bins] matmul — everything jits.  Dataset
downloads (paddle.audio.datasets) are out of scope in this zero-egress
environment; the feature layers are the API surface models consume.
"""

from . import features  # noqa: F401
from . import functional  # noqa: F401

__all__ = ["features", "functional"]
