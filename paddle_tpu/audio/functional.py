"""audio.functional: windows, mel filterbank, power/db conversion
(reference: python/paddle/audio/functional/window.py, functional.py)."""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "compute_fbank_matrix",
           "power_to_db", "create_dct", "fft_frequencies",
           "mel_frequencies"]


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    """Center frequencies of rFFT bins: linspace(0, sr/2, 1 + n_fft//2)
    (reference: audio/functional/functional.py fft_frequencies)."""
    return jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    """``n_mels`` frequencies evenly spaced on the mel scale between
    ``f_min`` and ``f_max`` (reference: functional.py mel_frequencies)."""
    mels = jnp.linspace(hz_to_mel(f_min, htk=htk),
                        hz_to_mel(f_max, htk=htk), n_mels)
    return mel_to_hz(mels, htk=htk).astype(dtype)


def get_window(window: str, win_length: int, fftbins: bool = True):
    """hann/hamming/blackman/ones — periodic (fftbins) like the ref."""
    n = jnp.arange(win_length)
    N = win_length if fftbins else win_length - 1
    if window in ("hann", "hanning"):
        return 0.5 - 0.5 * jnp.cos(2 * math.pi * n / N)
    if window == "hamming":
        return 0.54 - 0.46 * jnp.cos(2 * math.pi * n / N)
    if window == "blackman":
        return (0.42 - 0.5 * jnp.cos(2 * math.pi * n / N)
                + 0.08 * jnp.cos(4 * math.pi * n / N))
    if window in ("ones", "rectangular", "boxcar"):
        return jnp.ones(win_length)
    raise ValueError(f"unsupported window {window!r}")


def hz_to_mel(f, htk: bool = False):
    f = jnp.asarray(f, jnp.float32)
    if htk:
        return 2595.0 * jnp.log10(1.0 + f / 700.0)
    # slaney scale (reference default)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(f / min_log_hz) / logstep, mels)


def mel_to_hz(m, htk: bool = False):
    m = jnp.asarray(m, jnp.float32)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(m >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                     freqs)


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: str = "slaney"):
    """[n_mels, n_fft//2 + 1] triangular mel filterbank."""
    f_max = f_max if f_max is not None else sr / 2.0
    fft_freqs = fft_frequencies(sr, n_fft)
    hz_pts = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    lower = hz_pts[:-2][:, None]
    center = hz_pts[1:-1][:, None]
    upper = hz_pts[2:][:, None]
    up = (fft_freqs[None, :] - lower) / jnp.maximum(center - lower, 1e-10)
    down = (upper - fft_freqs[None, :]) / jnp.maximum(upper - center, 1e-10)
    fb = jnp.maximum(0.0, jnp.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb = fb * enorm[:, None]
    return fb


def power_to_db(x, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    x = jnp.asarray(x)
    db = 10.0 * jnp.log10(jnp.maximum(x, amin))
    db = db - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        db = jnp.maximum(db, jnp.max(db) - top_db)
    return db


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho"):
    """[n_mels, n_mfcc] DCT-II basis (reference create_dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * math.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].multiply(1.0 / math.sqrt(2.0))
    else:
        dct = dct * 2.0
    return dct
