"""Serving telemetry facade, updated by the engine OFF the hot path.

Rebased onto ``paddle_tpu.obs``: every counter/gauge/histogram lives in
an :class:`~paddle_tpu.obs.MetricsRegistry` (Prometheus text exposition,
JSON snapshot, windowed rates) and every request carries a lifecycle
span trace in a ring-buffered :class:`~paddle_tpu.obs.Tracer` — while
``snapshot()`` keeps the exact dict shape earlier rounds shipped, plus
p50/p99 TTFT and TPOT from the new log-bucketed histograms.

Every update is a host-side op on values the engine already holds (no
extra device syncs: the engine's single per-step token readback feeds
everything — pinned by tests/test_observability.py).  With
``record_events=True`` the engine additionally wraps each step in a
``profiler.RecordEvent`` and the tracer's request lanes merge into
``profiler.export_chrome_tracing`` output.

CLOCK BASE: all timestamps entering this class MUST be
``time.perf_counter()`` readings — ``Scheduler.submit`` stamps
``Request.arrival_time`` from that clock and :meth:`on_first_token`
rejects arrivals from any other base (a ``time.time()`` arrival used to
silently corrupt the TTFT mean; now it raises).

The metric glossary lives in docs/observability.md.
"""

from __future__ import annotations

import math
import time
from typing import Dict, Optional, Sequence, Tuple

from ..obs import Histogram, MetricsRegistry, Tracer

__all__ = ["ServingMetrics"]

# admission-projection clamps: a degenerate measurement window (one
# finish inside a denormal-small busy window, or a finish against an
# hours-long idle-heavy window) must yield a FINITE, bounded hint — a
# retry_after_s of inf/nan/1e6 seconds is not a hint, it is a bug
# surfaced to every rejected client.  Projections cap higher than hints:
# a projection only needs to stay comparable against deadlines, while a
# hint is an actual "come back in N seconds" told to a caller.
MAX_RETRY_AFTER_S = 600.0
MAX_PROJECTED_TTFT_S = 3600.0


class ServingMetrics:
    def __init__(self, record_events: bool = False,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None):
        # record_events=True wraps each step in a profiler.RecordEvent
        # AND merges the tracer's request lanes into chrome exports
        self.record_events = record_events
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = tracer if tracer is not None else Tracer()
        # disjoint lane block per engine: the step timeline sits on
        # engine_lane, request r on engine_lane + 1 + r — two engines
        # sharing one tracer never collide on a lane
        self.engine_lane = self.tracer.claim_lane_block()
        self.tracer.set_lane_name(self.engine_lane, "serving.engine",
                                  pin=True)
        if record_events:
            self.tracer.install_profiler_source()
        self._bind()

    def close(self) -> None:
        """Detach from the profiler's chrome-export source list (the one
        global this object registers into).  Long-lived processes that
        churn ``record_events=True`` engines MUST close them, or every
        later export merges the dead engines' lanes too.  Only balances
        what __init__ installed — a record_events=False engine's close
        must not decrement a shared tracer's refcount for its peers."""
        if self.record_events:
            self.record_events = False      # idempotent: one remove
            self.tracer.remove_profiler_source()

    def request_lane(self, request_id: int) -> int:
        """Tracer lane for one request, folded into this engine's lane
        block (ids are unbounded; lanes wrap inside the block so they
        can never walk into a neighbour engine's reservation — the span
        ring is far smaller than the block, so a wrapped lane's previous
        tenant has long been evicted)."""
        return self.engine_lane + 1 + request_id % (Tracer.LANE_BLOCK - 1)

    def _bind(self) -> None:
        """Get-or-create this engine's instruments in the registry.
        Binding never zeroes anything — constructing a second engine
        onto a SHARED registry/tracer must not wipe the first one's
        accumulated data (the instruments are then shared and both
        engines aggregate into them).  Everything bound here lands in
        ``self._own`` — the single list reset() iterates, so a new
        instrument can never be forgotten by reset."""
        reg = self.registry
        self._own = []
        own = self._own.append

        def c(*a, **kw):
            inst = reg.counter(*a, **kw)
            own(inst)
            return inst

        def h(*a, **kw):
            inst = reg.histogram(*a, **kw)
            own(inst)
            return inst

        def g(*a, **kw):
            inst = reg.gauge(*a, **kw)
            own(inst)
            return inst

        self._c_submitted = c("serving.requests_submitted",
                              "requests accepted by submit()")
        self._c_finished = c("serving.requests_finished",
                             "requests that reached eos/length")
        self._c_tokens = c("serving.tokens_generated",
                           "output tokens harvested")
        self._c_prefills = c("serving.prefills",
                             "completed request prefills")
        self._c_prefill_tokens = c("serving.prefill_tokens",
                                   "prompt tokens actually prefilled "
                                   "(uncached suffixes)")
        self._c_prefill_chunks = c("serving.prefill_chunks",
                                   "prefill chunk programs dispatched")
        self._c_prefill_chunk_tokens = c("serving.prefill_chunk_tokens",
                                         "real tokens covered by chunks")
        self._c_prefix_hits = c("serving.prefix_hits",
                                "admissions with a radix-cache match")
        self._c_prefix_hit_tokens = c("serving.prefix_hit_tokens",
                                      "prompt tokens served from cache")
        self._c_steps = c("serving.steps", "engine step() iterations")
        self._c_compiles = c("serving.compiles",
                             "program (re)traces seen by trace counters")
        self._h_ttft = h("serving.ttft_s",
                         "submit -> first generated token", unit="s")
        self._h_tpot = h("serving.tpot_s",
                         "per-output-token latency after the first",
                         unit="s")
        self._h_step = h("serving.step_s", "engine step wall time",
                         unit="s")
        self._h_chunk = h("serving.prefill_chunk_s",
                          "prefill chunk dispatch wall time", unit="s")
        self._h_queue_wait = h("serving.queue_wait_s",
                               "submit -> admission", unit="s")
        self._h_gather = h("serving.gather_s",
                           "prefix block gather / staging init", unit="s")
        self._h_decode_block = h("kernel.decode_block_s",
                                 "fused decode-block step dispatch wall "
                                 "time (engine fused_decode path)",
                                 unit="s")
        self._g_queue_depth = g("serving.queue_depth",
                                "waiting requests at the last step")
        self._g_occupancy = g("serving.slot_occupancy",
                              "occupied/total slots at the last step")
        # robustness surface (docs/serving.md "Fault tolerance"): the
        # terminal-status counters partition every submitted request —
        # finished + cancelled + deadline_exceeded + failed (+ rejected,
        # which never enters the queue) == submitted, once drained
        self._c_cancelled = c("serving.requests_cancelled",
                              "requests unwound by cancel()")
        self._c_deadline = c("serving.requests_deadline_exceeded",
                             "requests terminated by a blown deadline")
        self._c_failed = c("serving.requests_failed",
                           "requests terminally failed by a fault")
        self._c_rejected = c("serving.requests_rejected",
                             "submissions refused (backpressure/SLO/"
                             "circuit)")
        self._c_faults = c("serving.faults",
                           "faults observed by the watchdog (injected "
                           "or real)")
        self._c_retries = c("serving.step_retries",
                            "watchdog step retries (backoff sleeps)")
        self._c_quarantines = c("serving.quarantines",
                                "quarantine rebuilds of the device plane")
        self._g_health = g("serving.health_state",
                           "0 healthy / 1 degraded / 2 quarantined / "
                           "3 circuit_open")
        self._g_degradation = g("serving.degradation_level",
                                "optional subsystems disabled by the "
                                "degradation ladder")
        # tensor-parallel serving surface (docs/serving.md
        # "Tensor-parallel serving"): the mesh degree this engine
        # shards over, and the wall time of the collective-bearing
        # decode dispatch+readback — on a TP mesh every decode step's
        # latency includes its fused entry/exit collectives, so this
        # histogram IS the trace evidence the collectives ride the
        # step (compare its p50 against a tp=1 engine's
        # serving.phase.decode_dispatch_s)
        # the tp gauge binds OUTSIDE self._own: the degree is an
        # engine-lifetime constant published once at construction, and
        # the warmup->reset()->measure flow must not zero it into a
        # lying 0 on every later scrape (health_state survives reset by
        # being re-published each step; nothing re-publishes this)
        self._g_tp = reg.gauge("serving.tp_degree",
                               "tensor-parallel mesh degree "
                               "(1 = single chip)")
        self._h_collective = h("serving.collective_s",
                               "collective-bearing decode "
                               "dispatch+readback wall time (recorded "
                               "only on tp > 1 engines)", unit="s")
        # zero-cold-start surface (docs/serving.md "Zero cold start"):
        # warm-load accounting for the AOT program store.  The event
        # counters window-reset with the rest; the two gauges are
        # engine-lifetime facts (how long THIS engine's warm load took,
        # how long the store's build took) and bind outside self._own
        # for the same reason as serving.tp_degree — nothing would ever
        # re-publish them after a bench warmup reset
        self._c_aot_loads = c("aot.loads",
                              "programs warm-loaded from the AOT store "
                              "instead of traced")
        self._c_aot_misses = c("aot.misses",
                               "AOT lookups with no usable artifact "
                               "(fingerprint skew / leg not in store)")
        self._c_aot_fallbacks = c("aot.fallbacks",
                                  "AOT load attempts that failed "
                                  "(corrupt artifact, version skew, "
                                  "injected fault) and degraded to "
                                  "tracing")
        self._g_aot_load_s = reg.gauge("aot.load_s",
                                       "wall seconds the engine's last "
                                       "warm load spent")
        self._g_aot_build_s = reg.gauge("aot.build_s",
                                        "wall seconds the attached "
                                        "store's builder spent "
                                        "exporting (from the store "
                                        "index)")
        # speculative-decoding surface (docs/serving.md "Speculative
        # decoding"): draft tokens proposed vs draft tokens the verify
        # program accepted.  accepted/draft is the acceptance rate — the
        # single number that predicts the speedup (each accepted token
        # is a decode step the engine did not pay for)
        self._c_spec_draft = c("spec.draft_tokens",
                               "draft tokens proposed by the n-gram "
                               "tables (verify-window fill)")
        self._c_spec_accept = c("spec.accepted_tokens",
                                "draft tokens the verify program "
                                "accepted (free decode steps)")
        self._last_health_state: Optional[str] = None
        self._phase_h: Dict[str, Histogram] = {}
        self._zero_local()

    def _zero_local(self) -> None:
        # per-ENGINE tallies feeding the derived rates: with a shared
        # registry the counters aggregate the whole fleet, so dividing
        # them by this engine's busy time would inflate every rate —
        # rates and ratios always describe THIS engine
        self._busy_s = 0.0
        self._queue_depth_sum = 0
        self._occupancy_sum = 0.0
        self._tokens_local = 0
        self._steps_local = 0
        self._finished_local = 0
        self._spec_draft_local = 0
        self._spec_accept_local = 0

    def reset(self) -> None:
        """Zero THIS engine's instruments and drop the tracer's recorded
        spans/events (fresh measurement window — bench warmup vs
        measure).  Only the serving instruments bound here reset; other
        producers' metrics in a shared registry (a trainer's ``train.*``
        histograms) are untouched.  A shared TRACER's ring is one buffer,
        so its clear does drop every producer's spans — give each engine
        its own tracer when traces must survive a neighbour's reset."""
        for inst in (*self._own, *self._phase_h.values()):
            inst.reset()
        self.tracer.clear()
        self._zero_local()

    # ------------------------------------------------------------ events
    def on_submit(self, n: int = 1) -> None:
        self._c_submitted.inc(n)

    def on_prefill(self, prompt_len: int) -> None:
        """One request's prefill completed; ``prompt_len`` counts only
        the tokens the model actually ran (the uncached suffix) — the
        FLOPs-saved story is ``prefix_hit_tokens`` vs this."""
        self._c_prefills.inc()
        self._c_prefill_tokens.inc(prompt_len)

    def on_prefill_chunk(self, tokens: int,
                         seconds: Optional[float] = None) -> None:
        """One chunk program dispatched, covering ``tokens`` real (non-
        padding) prompt tokens over ``seconds`` of host dispatch time."""
        self._c_prefill_chunks.inc()
        self._c_prefill_chunk_tokens.inc(tokens)
        if seconds is not None:
            self._h_chunk.observe(seconds)

    def on_prefix_hit(self, tokens: int) -> None:
        """Admission matched ``tokens`` prompt tokens in the radix cache
        (their KV was copied, not recomputed)."""
        self._c_prefix_hits.inc()
        self._c_prefix_hit_tokens.inc(tokens)

    def on_queue_wait(self, seconds: float) -> None:
        self._h_queue_wait.observe(seconds)

    def on_gather(self, seconds: float) -> None:
        self._h_gather.observe(seconds)

    def on_decode_block(self, active: bool, reason: Optional[str],
                        step: int = 0, tp: int = 1) -> None:
        """The engine resolved its decode path (emitted once, when the
        single decode program is built): ``active`` says whether the
        fused decode-block kernels compiled in, ``reason`` carries the
        fallback cause when the flag asked for fusion but routing or
        legality refused (None when fused engaged or the flag was off),
        and ``tp`` records the mesh degree — ``active`` at ``tp > 1``
        means the SHARDED block (kernels/decode_block_tp.py), so traces
        from a shared registry separate the two fused variants.  Lands
        as a ``decode_block`` discrete event on the engine lane
        (glossary: docs/observability.md)."""
        self.tracer.event("decode_block", lane=self.engine_lane,
                          active=active,
                          reason=reason if reason is not None else "",
                          step=step, tp=tp)

    def on_aot_load(self, programs: int, seconds: float,
                    build_s: Optional[float] = None) -> None:
        """The engine finished a warm load: ``programs`` artifacts
        installed from the AOT store in ``seconds`` of wall time
        (``build_s``: the store's recorded builder time, republished as
        the ``aot.build_s`` gauge so one scrape shows both halves of
        the build-once/load-many trade).  Lands as an ``aot_load``
        discrete event on the engine lane."""
        self._c_aot_loads.inc(programs)
        self._g_aot_load_s.set(seconds)
        if build_s is not None:
            self._g_aot_build_s.set(build_s)
        self.tracer.event("aot_load", lane=self.engine_lane,
                          programs=programs, seconds=round(seconds, 6))

    def on_aot_miss(self, program: str, reason: str) -> None:
        """An AOT lookup found no usable artifact (store fingerprint
        skew, or ``program``'s leg absent) — the engine traces instead.
        A degradation event (``aot_miss``), never an error."""
        self._c_aot_misses.inc()
        self.tracer.event("aot_miss", lane=self.engine_lane,
                          program=program, reason=reason)

    def on_aot_fallback(self, program: str, reason: str) -> None:
        """An AOT load ATTEMPT failed (corrupt artifact, deserialize
        skew, injected ``aot_load`` fault) and ``program`` degraded to
        trace-on-demand.  Lands as an ``aot_fallback`` event."""
        self._c_aot_fallbacks.inc()
        self.tracer.event("aot_fallback", lane=self.engine_lane,
                          program=program, reason=reason)

    def on_decode_block_step(self, seconds: float) -> None:
        """One fused-path decode dispatch's wall time (the engine calls
        this only on steps whose decode ran the fused kernel pair, so
        the ``kernel.decode_block_s`` histogram is separable from the
        unfused ``serving.phase.decode_dispatch_s`` in one registry)."""
        self._h_decode_block.observe(seconds)

    def set_tp_degree(self, tp: int) -> None:
        self._g_tp.set(tp)

    def on_collective(self, seconds: float) -> None:
        """One TP decode step's collective-bearing dispatch+readback
        time (the engine calls this only when ``tp > 1``)."""
        self._h_collective.observe(seconds)

    def on_compile(self, program: str, n: int = 1) -> None:
        self._c_compiles.inc(n)

    def on_first_token(self, arrival_t: float,
                       now: Optional[float] = None) -> None:
        """Record one TTFT sample.  ``arrival_t`` MUST be a
        ``time.perf_counter()`` reading (``Request.arrival_time`` as
        ``Scheduler.submit`` stamps it).  A ``time.time()`` arrival sits
        decades ahead of the perf_counter epoch, so the mismatch is
        detected and raised instead of silently feeding a garbage mean
        (the pre-obs bug this signature change fixes)."""
        if now is None:
            now = time.perf_counter()
        ttft = now - arrival_t
        if ttft < 0:
            raise ValueError(
                f"on_first_token: arrival_t {arrival_t!r} is ahead of "
                f"perf_counter now {now!r} — arrival timestamps must be "
                f"time.perf_counter() readings, not time.time() (mixed "
                f"clock bases corrupt TTFT)")
        self._h_ttft.observe(ttft)

    def on_output_token(self, seconds: float) -> None:
        """One decode token's latency since the request's previous
        token (TPOT — the steady-state per-token serving cost)."""
        self._h_tpot.observe(seconds)

    def on_finish(self, n: int = 1) -> None:
        self._c_finished.inc(n)
        self._finished_local += n

    # ----------------------------------------------- robustness events
    def on_terminal(self, status: str, reason: str, request_id: int,
                    now: Optional[float] = None) -> None:
        """One request reached an ABNORMAL terminal status (normal
        completion goes through :meth:`on_finish`): count it and drop a
        discrete event on the request's lane so the trace shows why the
        lifecycle ended."""
        counter = {"cancelled": self._c_cancelled,
                   "deadline_exceeded": self._c_deadline,
                   "failed": self._c_failed,
                   "rejected": self._c_rejected}.get(status)
        if counter is None:
            raise ValueError(f"unknown terminal status {status!r}")
        counter.inc()
        self.tracer.event("request_" + status,
                          lane=self.request_lane(request_id),
                          t=now, request=request_id, reason=reason)

    def on_fault(self, site: str, error: str, step: int = 0) -> None:
        """The watchdog observed one fault (injected or real) attributed
        to ``site`` (an injection-point or subsystem name)."""
        self._c_faults.inc()
        self.tracer.event("fault", lane=self.engine_lane, site=site,
                          error=error[:200], step=step)

    def on_retry(self, attempt: int, backoff_s: float,
                 step: int = 0) -> None:
        self._c_retries.inc()
        self.tracer.event("step_retry", lane=self.engine_lane,
                          attempt=attempt, backoff_s=round(backoff_s, 4),
                          step=step)

    def on_spec(self, drafted: int, accepted: int) -> None:
        """One speculative step's draft/accept tally (the engine calls
        this after the harvest of a verify window, never between device
        dispatches)."""
        self._c_spec_draft.inc(drafted)
        self._c_spec_accept.inc(accepted)
        self._spec_draft_local += drafted
        self._spec_accept_local += accepted

    def on_spec_disable(self, reason: str) -> None:
        """The degradation ladder (or an unsatisfiable constraint)
        turned speculation off — drop the discrete event so the trace
        shows when the engine fell back to one token per step."""
        self.tracer.event("spec_disable", lane=self.engine_lane,
                          reason=reason[:200])

    def on_degrade(self, subsystem: str, level: int, reason: str) -> None:
        """The degradation ladder disabled an optional subsystem; the
        gauge tracks the ladder level, the event carries which and why."""
        self._g_degradation.set(level)
        self.tracer.event("degrade", lane=self.engine_lane,
                          subsystem=subsystem, level=level,
                          reason=reason[:200])

    def on_health_state(self, state: str, code: int,
                        step: int = 0) -> None:
        """Track the health state machine: the gauge always reflects the
        latest state; the discrete event fires only on TRANSITIONS so
        a million healthy steps cost one event, not a million."""
        self._g_health.set(code)
        if state != self._last_health_state:
            self.tracer.event("health_state", lane=self.engine_lane,
                              state=state, step=step)
            self._last_health_state = state

    def on_quarantine(self, phase: str, reason: str, step: int = 0,
                      seconds: Optional[float] = None) -> None:
        """``phase`` is "enter" or "leave"; one quarantine rebuild
        counts once (on enter)."""
        if phase == "enter":
            self._c_quarantines.inc()
        attrs = {"reason": reason[:200], "step": step}
        if seconds is not None:
            attrs["seconds"] = round(seconds, 4)
        self.tracer.event(f"quarantine_{phase}", lane=self.engine_lane,
                          **attrs)

    # -------------------------------------------- admission projections
    @property
    def completion_rate(self) -> Optional[float]:
        """Requests completed per second of engine busy time — the live
        throughput estimate backpressure hints derive from (None until
        at least one request finished in this window).  Degenerate
        windows — a finish counted against a denormal-small or infinite
        busy time, where the division returns inf or 0.0 — also report
        None: the hint/projection corners below must never divide by a
        zero rate (a 0.0 rate used to raise ZeroDivisionError out of
        ``retry_after_hint``, and an inf rate projected a 0.0 TTFT that
        admitted hopeless requests)."""
        if self._finished_local <= 0 or self._busy_s <= 0:
            return None
        rate = self._finished_local / self._busy_s
        if not math.isfinite(rate) or rate <= 0.0:
            return None
        return rate

    def retry_after_hint(self, excess: int = 1) -> Optional[float]:
        """Seconds until ~``excess`` queue positions should free, from
        the live completion rate.  None with no history — callers
        surface that as "no hint" rather than inventing a number.
        Always finite and clamped to :data:`MAX_RETRY_AFTER_S`: a
        near-zero rate (one finish against an idle-heavy window) must
        not tell a client to come back in 1e6 seconds."""
        rate = self.completion_rate
        if rate is None:
            return None
        return min(max(excess, 1) / rate, MAX_RETRY_AFTER_S)

    def projected_ttft_s(self, queue_depth: int) -> Optional[float]:
        """SLO-aware admission estimate: time for the current queue to
        drain ahead of a new arrival plus the live p50 TTFT.  A
        heuristic, deliberately simple — it only needs to be right
        enough to reject requests that are HOPELESSLY late, not to
        schedule precisely.  None with no history (cold engines admit;
        rejecting on zero data would deadlock the very first request);
        otherwise finite, clamped to :data:`MAX_PROJECTED_TTFT_S` so
        deadline comparisons never meet an inf/nan."""
        rate = self.completion_rate
        if rate is None:
            return None
        base = self._h_ttft.quantile(0.50) or 0.0
        return min(queue_depth / rate + base, MAX_PROJECTED_TTFT_S)

    def record_step(self, active_slots: int, num_slots: int,
                    queue_depth: int, new_tokens: int,
                    step_seconds: float, step_index: int = 0,
                    phases: Optional[Sequence[Tuple[str, float, float]]]
                    = None) -> None:
        """One engine step's accounting (called after the token harvest —
        never between device dispatches).  ``phases`` is the step's
        timeline breakdown as ``(name, start, end)`` perf_counter
        triples; each lands in a ``serving.phase.<name>_s`` histogram
        and as a ``step.<name>`` span on the engine lane."""
        occupancy = active_slots / max(num_slots, 1)
        self._c_steps.inc()
        self._c_tokens.inc(new_tokens)
        self._busy_s += step_seconds
        self._queue_depth_sum += queue_depth
        self._occupancy_sum += occupancy
        self._tokens_local += new_tokens
        self._steps_local += 1
        self._g_queue_depth.set(queue_depth)
        self._g_occupancy.set(occupancy)
        self._h_step.observe(step_seconds)
        if phases:
            for name, start, end in phases:
                hp = self._phase_h.get(name)
                if hp is None:
                    hp = self.registry.histogram(
                        f"serving.phase.{name}_s",
                        f"step phase: {name}", unit="s")
                    self._phase_h[name] = hp
                hp.observe(end - start)
                self.tracer.add_span(f"step.{name}", self.engine_lane,
                                     start, end, step=step_index)

    # --------------------------------------------------------- counters
    # lifetime counts read as plain ints (the pre-registry attribute API)
    @property
    def requests_submitted(self) -> int:
        return self._c_submitted.value

    @property
    def requests_finished(self) -> int:
        return self._c_finished.value

    @property
    def tokens_generated(self) -> int:
        return self._c_tokens.value

    @property
    def prefills(self) -> int:
        return self._c_prefills.value

    @property
    def prefill_tokens(self) -> int:
        return self._c_prefill_tokens.value

    @property
    def prefill_chunks(self) -> int:
        return self._c_prefill_chunks.value

    @property
    def prefill_chunk_tokens(self) -> int:
        return self._c_prefill_chunk_tokens.value

    @property
    def prefix_hits(self) -> int:
        return self._c_prefix_hits.value

    @property
    def prefix_hit_tokens(self) -> int:
        return self._c_prefix_hit_tokens.value

    @property
    def steps(self) -> int:
        return self._c_steps.value

    # ---------------------------------------------------------- derived
    @property
    def mean_ttft_ms(self) -> Optional[float]:
        m = self._h_ttft.mean
        return None if m is None else 1e3 * m

    def _q_ms(self, hist: Histogram, q: float) -> Optional[float]:
        v = hist.quantile(q)
        return None if v is None else 1e3 * v

    @property
    def ttft_p50_ms(self) -> Optional[float]:
        return self._q_ms(self._h_ttft, 0.50)

    @property
    def ttft_p99_ms(self) -> Optional[float]:
        return self._q_ms(self._h_ttft, 0.99)

    @property
    def tpot_p50_ms(self) -> Optional[float]:
        return self._q_ms(self._h_tpot, 0.50)

    @property
    def tpot_p99_ms(self) -> Optional[float]:
        return self._q_ms(self._h_tpot, 0.99)

    # rates/ratios divide per-engine tallies by per-engine denominators:
    # under a shared registry the counter properties above aggregate the
    # fleet, and mixing the two would inflate every derived value
    @property
    def tokens_per_sec(self) -> Optional[float]:
        if self._busy_s <= 0:
            return None
        return self._tokens_local / self._busy_s

    @property
    def batch_fill_ratio(self) -> Optional[float]:
        if self._steps_local == 0:
            return None
        return self._occupancy_sum / self._steps_local

    @property
    def mean_queue_depth(self) -> Optional[float]:
        if self._steps_local == 0:
            return None
        return self._queue_depth_sum / self._steps_local

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        """Accepted / drafted over THIS engine's window (None until the
        first speculative step) — the number that predicts the
        speculative speedup."""
        if self._spec_draft_local <= 0:
            return None
        return self._spec_accept_local / self._spec_draft_local

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, object]:
        """The engine-counter dict earlier rounds shipped, extended with
        the histogram quantiles (keys only ever ADD — consumers pin on
        key presence).  The full instrument dump (every histogram's
        count/sum/p50/p90/p99) is ``self.registry.snapshot()``."""
        r = lambda v, nd=4: None if v is None else round(v, nd)
        return {
            "requests_submitted": self.requests_submitted,
            "requests_finished": self.requests_finished,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "prefill_tokens": self.prefill_tokens,
            "prefill_chunks": self.prefill_chunks,
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "steps": self.steps,
            "tokens_per_sec": r(self.tokens_per_sec, 1),
            "mean_ttft_ms": r(self.mean_ttft_ms, 2),
            "ttft_p50_ms": r(self.ttft_p50_ms, 2),
            "ttft_p99_ms": r(self.ttft_p99_ms, 2),
            "tpot_p50_ms": r(self.tpot_p50_ms, 3),
            "tpot_p99_ms": r(self.tpot_p99_ms, 3),
            "batch_fill_ratio": r(self.batch_fill_ratio),
            "mean_queue_depth": r(self.mean_queue_depth, 2),
            # robustness block (keys only ever ADD — see class docstring)
            "requests_cancelled": self._c_cancelled.value,
            "requests_deadline_exceeded": self._c_deadline.value,
            "requests_failed": self._c_failed.value,
            "requests_rejected": self._c_rejected.value,
            "faults": self._c_faults.value,
            "step_retries": self._c_retries.value,
            "quarantines": self._c_quarantines.value,
            "health_state": self._g_health.value,
            "degradation_level": self._g_degradation.value,
            # speculative decoding block (keys only ever ADD)
            "spec_draft_tokens": self._c_spec_draft.value,
            "spec_accepted_tokens": self._c_spec_accept.value,
            "spec_acceptance_rate": r(self.spec_acceptance_rate),
        }
