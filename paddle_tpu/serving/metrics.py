"""Serving counters, updated by the engine OFF the hot path.

Every update is a host-side float/int op on values the engine already
holds (no extra device syncs: the engine's single per-step token readback
feeds everything).  Exposed as a plain dict (``snapshot()``) and logged
through the profiler's host-event tree: with ``record_events=True`` the
engine wraps each step's prefill/decode phases in
``profiler.RecordEvent`` annotations, so ``export_chrome_tracing``
timelines show the serving loop alongside device activity.

Glossary (docs/serving.md has the full definitions):
  * ttft            — submit -> first generated token, per request;
  * tokens/s        — generated tokens over the engine's busy wall time;
  * queue depth     — waiting requests at each step;
  * slot occupancy  — occupied/total slots at each step;
  * batch fill      — mean occupancy over steps: the fraction of the
    fixed-shape decode batch doing useful work (THE continuous-batching
    payoff metric — static batching idles slots that finished early).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

__all__ = ["ServingMetrics"]


class ServingMetrics:
    def __init__(self, record_events: bool = False):
        # record_events=True wraps each step in a profiler.RecordEvent so
        # host traces (profiler.export_chrome_tracing) carry serving steps
        self.record_events = record_events
        self.reset()

    def reset(self) -> None:
        self.requests_submitted = 0
        self.requests_finished = 0
        self.tokens_generated = 0
        self.prefills = 0
        self.prefill_tokens = 0
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        self.steps = 0
        self._busy_s = 0.0
        self._ttfts: List[float] = []
        self._queue_depth_sum = 0
        self._occupancy_sum = 0.0

    # ------------------------------------------------------------ events
    def on_submit(self, n: int = 1) -> None:
        self.requests_submitted += n

    def on_prefill(self, prompt_len: int) -> None:
        """One request's prefill completed; ``prompt_len`` counts only
        the tokens the model actually ran (the uncached suffix) — the
        FLOPs-saved story is ``prefix_hit_tokens`` vs this."""
        self.prefills += 1
        self.prefill_tokens += prompt_len

    def on_prefill_chunk(self, tokens: int) -> None:
        """One chunk program dispatched, covering ``tokens`` real (non-
        padding) prompt tokens."""
        self.prefill_chunks += 1
        self.prefill_chunk_tokens += tokens

    def on_prefix_hit(self, tokens: int) -> None:
        """Admission matched ``tokens`` prompt tokens in the radix cache
        (their KV was copied, not recomputed)."""
        self.prefix_hits += 1
        self.prefix_hit_tokens += tokens

    def on_first_token(self, arrival_time: float) -> None:
        self._ttfts.append(time.perf_counter() - arrival_time)

    def on_finish(self, n: int = 1) -> None:
        self.requests_finished += n

    def record_step(self, active_slots: int, num_slots: int,
                    queue_depth: int, new_tokens: int,
                    step_seconds: float) -> None:
        """One engine step's accounting (called after the token harvest —
        never between device dispatches)."""
        self.steps += 1
        self.tokens_generated += new_tokens
        self._busy_s += step_seconds
        self._queue_depth_sum += queue_depth
        self._occupancy_sum += active_slots / max(num_slots, 1)

    # ---------------------------------------------------------- snapshot
    @property
    def mean_ttft_ms(self) -> Optional[float]:
        if not self._ttfts:
            return None
        return 1e3 * sum(self._ttfts) / len(self._ttfts)

    @property
    def tokens_per_sec(self) -> Optional[float]:
        if self._busy_s <= 0:
            return None
        return self.tokens_generated / self._busy_s

    @property
    def batch_fill_ratio(self) -> Optional[float]:
        if self.steps == 0:
            return None
        return self._occupancy_sum / self.steps

    @property
    def mean_queue_depth(self) -> Optional[float]:
        if self.steps == 0:
            return None
        return self._queue_depth_sum / self.steps

    def snapshot(self) -> Dict[str, object]:
        r = lambda v, nd=4: None if v is None else round(v, nd)
        return {
            "requests_submitted": self.requests_submitted,
            "requests_finished": self.requests_finished,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "prefill_tokens": self.prefill_tokens,
            "prefill_chunks": self.prefill_chunks,
            "prefill_chunk_tokens": self.prefill_chunk_tokens,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "steps": self.steps,
            "tokens_per_sec": r(self.tokens_per_sec, 1),
            "mean_ttft_ms": r(self.mean_ttft_ms, 2),
            "batch_fill_ratio": r(self.batch_fill_ratio),
            "mean_queue_depth": r(self.mean_queue_depth, 2),
        }
