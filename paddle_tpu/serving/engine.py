"""Continuous-batching engine core: the fixed-shape step loop.

Device plane (all jitted, all fixed-shape — graftlint's recompile-hazard
rule is the design constraint):

  * ``prefill``  — one program per LENGTH BUCKET: ``[1, bucket]`` prompt
    into a fresh ``[1, max_seq]`` cache, returning the last-valid-token
    logits (a traced prompt length selects the row, so padding never
    recompiles) and the cache the pool adopts into the request's slot;
  * ``decode``   — ONE program, period: ``[num_slots, 1]`` tokens against
    the whole pool with per-slot positions (models/kv_cache.py), per-slot
    sampling params as traced row values, and per-slot PRNG keys.  Free
    slots ride along as no-ops: their rows decode garbage that nothing
    reads, their writes land at position 0 of a row the next adopt
    overwrites wholesale.

Host plane: ONE device->host readback per step phase — the decode
harvest reads the sampled token vector once, and a step that admits
requests reads their batched first tokens once (all prefill dispatches
stay async until then).  Admission, eviction, eos/length bookkeeping and
metrics all run on host ints the engine already holds.

Per-slot sampling reuses ``generation._filter_top_p`` directly (its
threshold broadcasts over rows) and generalises ``_filter_top_k`` to a
per-row traced k via rank masking (``_filter_top_k_rows`` — the static-k
form cannot vary k within one compiled step).  Each slot draws from its
OWN PRNG key with the same split discipline as ``generate``, so a
single-request engine run reproduces ``generate(seed=...)`` token for
token, sampling included.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import _filter_top_p
from .kv_pool import KVPool
from .metrics import ServingMetrics
from .scheduler import Request, Scheduler

__all__ = ["EngineCore", "sample_rows"]


def _filter_top_k_rows(logits, top_k):
    """Per-row top-k: keep each row's ``top_k[r]`` highest logits
    (``top_k[r] == 0`` keeps the whole row).  Rank masking — argsort of
    the descending argsort — matches ``generation._filter_top_k`` for
    distinct values and resolves ties by vocab order (the stable-sort
    winner), which is also what argmax picks for k=1."""
    order = jnp.argsort(-logits, axis=-1)
    rank = jnp.argsort(order, axis=-1)
    k = jnp.asarray(top_k, jnp.int32)[:, None]
    keep = jnp.where(k > 0, rank < k, True)
    return jnp.where(keep, logits, -jnp.inf)


def sample_rows(keys, logits, do_sample, temperature, top_k, top_p):
    """Per-row token selection over ``logits [rows, vocab]``.

    ``do_sample [rows] bool`` picks greedy argmax vs sampling per row;
    sampling rows apply ``temperature -> top_k -> top_p`` (the exact
    pipeline of ``generation.generate``) and draw from their OWN key row
    of ``keys [rows, key_dim]``, so one request's randomness never
    depends on its slot neighbours."""
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits / temp[:, None]
    filtered = _filter_top_k_rows(scaled, top_k)
    p = jnp.asarray(top_p, jnp.float32)[:, None]
    # rows with top_p == 1.0 skip the nucleus filter EXACTLY, matching
    # generate()'s static skip; filtered rows take the nucleus lane
    filtered = jnp.where(p >= 1.0, filtered, _filter_top_p(filtered, p))
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(jnp.asarray(do_sample, bool), sampled, greedy_tok)


class _Slot:
    """Host mirror of one pool slot's request progress."""

    __slots__ = ("req", "pos")

    def __init__(self, req: Request, prompt_len: int):
        self.req = req
        self.pos = prompt_len       # cache length == next write offset


class EngineCore:
    """Owns the pool, the per-slot device state and the compiled step
    functions.  The public request/streaming surface lives in
    ``serving.api.ServingEngine``."""

    def __init__(self, model, num_slots: int = 8,
                 max_seq: Optional[int] = None,
                 min_bucket: int = 16,
                 max_prefills_per_step: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None):
        self.model = model
        self.pool = KVPool.create(model, num_slots, max_seq)
        self.scheduler = Scheduler(num_slots, self.pool.max_seq,
                                   min_bucket=min_bucket,
                                   max_prefills_per_step=max_prefills_per_step)
        self.metrics = metrics or ServingMetrics()
        self.num_slots = num_slots
        self._slots: Dict[int, _Slot] = {}
        # per-slot device row state (fixed [num_slots] shapes)
        self._last_tok = jnp.zeros((num_slots,), jnp.int32)
        key0 = jax.random.PRNGKey(0)
        self._keys = jnp.tile(key0[None], (num_slots,) + (1,) * key0.ndim)
        # per-slot sampling params: host numpy mirrors, re-uploaded to a
        # cached device copy only when admission/eviction dirties them
        # (values are traced row data — changing them never recompiles)
        self._do_sample = np.zeros((num_slots,), bool)
        self._temperature = np.ones((num_slots,), np.float32)
        self._top_k = np.zeros((num_slots,), np.int32)
        self._top_p = np.ones((num_slots,), np.float32)
        self._sampling_dev: Optional[Tuple] = None
        # compiled programs: ONE decode fn + ONE prefill fn whose jit
        # cache is keyed by the [1, bucket] input shape (one program per
        # bucket, nothing per length); the trace counters are what the
        # compile-count guard test asserts on
        self._decode_fn = None
        self._prefill_fn: Optional[Callable] = None
        self.trace_counts = {"prefill": 0, "decode": 0}

    # ----------------------------------------------------------- prefill
    def _build_prefill_fn(self) -> Callable:
        model, max_seq = self.model, self.pool.max_seq

        def prefill(ids, length):
            self.trace_counts["prefill"] += 1  # trace-time side effect
            caches = model.init_cache(1, max_seq)
            logits, caches = model.decode_step(ids, caches, 0)
            last = jnp.take_along_axis(
                logits, (length - 1)[None, None, None], axis=1)[0, 0]
            return last.astype(jnp.float32), caches

        return jax.jit(prefill)

    def _admit(self, admitted: List[Tuple[Request, int]]) -> int:
        """Prefill each admitted request into a pool slot and sample its
        first token with the request's own key.  All dispatches stay
        async; the admitted first tokens come back in ONE readback at the
        end (the decode harvest is the step's other one).  Returns tokens
        emitted."""
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill_fn()
        staged: List[Tuple[int, jax.Array]] = []
        for req, bucket in admitted:
            slot = self.pool.alloc()
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :req.prompt_len] = np.asarray(req.prompt, np.int32)
            last_logits, caches = self._prefill_fn(
                jnp.asarray(ids), jnp.asarray(req.prompt_len, jnp.int32))
            self.pool.adopt(slot, caches, req.prompt_len)
            key = jax.random.PRNGKey(req.sampling.seed)
            key, sub = jax.random.split(key)
            s = req.sampling
            first = sample_rows(
                sub[None], last_logits[None],
                jnp.asarray([s.do_sample]),
                jnp.asarray([s.temperature], jnp.float32),
                jnp.asarray([s.top_k], jnp.int32),
                jnp.asarray([s.top_p], jnp.float32))
            self.scheduler.place(req, slot)
            self._slots[slot] = _Slot(req, req.prompt_len)
            self._last_tok = self._last_tok.at[slot].set(first[0])
            self._keys = self._keys.at[slot].set(key)
            self._do_sample[slot] = s.do_sample
            self._temperature[slot] = s.temperature
            self._top_k[slot] = s.top_k
            self._top_p[slot] = s.top_p
            self._sampling_dev = None
            self.metrics.on_prefill(req.prompt_len)
            staged.append((slot, first))
        if staged:
            toks = np.asarray(jnp.concatenate([f for _, f in staged]))
            for (slot, _), tok in zip(staged, toks):
                self._emit(slot, int(tok), first_token=True)
        return len(staged)

    # ------------------------------------------------------------ decode
    def _build_decode_fn(self) -> Callable:
        model = self.model

        def decode(ks, vs, seq_pos, last_tok, keys, do_sample,
                   temperature, top_k, top_p):
            self.trace_counts["decode"] += 1  # trace-time side effect
            caches = [(k, v, seq_pos) for k, v in zip(ks, vs)]
            logits, caches = model.decode_step(last_tok[:, None], caches,
                                               seq_pos)
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            nxt = sample_rows(split[:, 1], logits[:, 0], do_sample,
                              temperature, top_k, top_p)
            new_ks = [c[0] for c in caches]
            new_vs = [c[1] for c in caches]
            return (new_ks, new_vs, caches[0][2], nxt.astype(jnp.int32),
                    split[:, 0])

        # donating the KV slabs aliases them in place — pool memory stays
        # a single allocation across the whole serving run
        return jax.jit(decode, donate_argnums=(0, 1))

    def _decode_all_slots(self) -> np.ndarray:
        """ONE fixed-shape decode step over every slot; returns the
        sampled token per slot (the step's single host readback)."""
        if self._decode_fn is None:
            self._decode_fn = self._build_decode_fn()
        if self._sampling_dev is None:
            self._sampling_dev = (jnp.asarray(self._do_sample),
                                  jnp.asarray(self._temperature),
                                  jnp.asarray(self._top_k),
                                  jnp.asarray(self._top_p))
        ks, vs, pos, nxt, self._keys = self._decode_fn(
            self.pool.ks, self.pool.vs, self.pool.seq_pos,
            self._last_tok, self._keys, *self._sampling_dev)
        self.pool.ks, self.pool.vs, self.pool.seq_pos = ks, vs, pos
        self._last_tok = nxt
        return np.asarray(nxt)

    # -------------------------------------------------------- step loop
    def step(self) -> int:
        """One engine iteration: admit+prefill, one decode step over all
        active slots, harvest tokens / evict finished.  Returns the
        number of requests still in flight (running + queued)."""
        t0 = time.perf_counter()
        ann = None
        if self.metrics.record_events:
            from ..profiler import RecordEvent
            ann = RecordEvent("serving.step")
            ann.begin()
        new_tokens = self._admit(self.scheduler.admit(self.pool.free_slots))
        if self._slots:
            toks = self._decode_all_slots()
            for slot in sorted(self._slots):
                new_tokens += self._harvest(slot, int(toks[slot]))
        self._evict_finished()
        if ann is not None:
            ann.end()
        self.metrics.record_step(
            active_slots=len(self._slots), num_slots=self.num_slots,
            queue_depth=self.scheduler.queue_depth,
            new_tokens=new_tokens,
            step_seconds=time.perf_counter() - t0)
        return len(self._slots) + self.scheduler.queue_depth

    def _emit(self, slot: int, tok: int, first_token: bool = False) -> None:
        req = self._slots[slot].req
        req.tokens.append(tok)
        if first_token:
            req.first_token_time = time.perf_counter()
            self.metrics.on_first_token(req.arrival_time)
        if req.stream is not None:
            req.stream(req, tok)
        eos = req.eos_token_id
        if eos is not None and tok == eos:
            req.finished, req.finish_reason = True, "eos"
        elif len(req.tokens) >= req.max_new_tokens:
            req.finished, req.finish_reason = True, "length"

    def _harvest(self, slot: int, tok: int) -> int:
        st = self._slots[slot]
        if st.req.finished:
            return 0  # finished at admit (eos/length on the first token)
        st.pos += 1
        self._emit(slot, tok)
        return 1

    def _evict_finished(self) -> None:
        for slot in [s for s, st in self._slots.items() if st.req.finished]:
            req = self.scheduler.release(slot)
            req.finish_time = time.perf_counter()
            self.pool.free(slot)
            del self._slots[slot]
            self._do_sample[slot] = False
            self._sampling_dev = None
            self.metrics.on_finish()

    # ----------------------------------------------------- conveniences
    def run_until_complete(self, max_steps: Optional[int] = None) -> int:
        """Step until queue and slots drain; returns steps taken."""
        steps = 0
        while self.scheduler.has_work():
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"serving did not drain within {max_steps} steps")
            self.step()
            steps += 1
        return steps
