"""Continuous-batching engine core: the fixed-shape step loop.

Device plane (all jitted, all fixed-shape — graftlint's recompile-hazard
rule is the design constraint):

  * ``prefill``  — one program per CHUNK WIDTH: ``[1, width]`` tokens
    appended into a ``[1, max_seq]`` staging cache at a traced offset,
    returning the last-valid-token logits (a traced valid count selects
    the row, so padding never recompiles).  Width comes from the
    scheduler's chunk plan: without chunking, one pow2-bucketed chunk
    covers the whole uncached suffix (the classic shape); with
    ``prefill_chunk`` set, long suffixes run as fixed-width pieces
    interleaved with decode, so one 8k admission never stalls the
    in-flight streams for more than one chunk;
  * ``block copy`` — the radix prefix cache's two programs
    (kv_pool.BlockPool): gather matched prefix blocks into the staging
    cache at admission, scatter freshly computed blocks out of the slot
    at prefill completion.  A cache-hit request prefills ONLY its
    suffix — prefill FLOPs drop by the shared-prefix fraction and TTFT
    becomes O(suffix);
  * ``decode``   — ONE program, period: ``[num_slots, 1]`` tokens against
    the whole pool with per-slot positions (models/kv_cache.py), per-slot
    sampling params as traced row values, and per-slot PRNG keys.  Free
    and mid-prefill slots ride along as no-ops: their rows decode garbage
    that nothing reads, their writes land at positions a later adopt
    overwrites wholesale;
  * ``verify``   — ONE program (speculative decoding, ``spec_k > 0``):
    ``[num_slots, spec_k+1]`` draft windows — each slot's last committed
    token followed by its host-proposed n-gram draft (serving/spec.py) —
    at per-slot positions, with matched-sampling acceptance computed
    in-program: the window replays the EXACT per-token split/sample
    chain sequential decode would run, a slot commits its longest
    draft prefix that matches those samples plus one bonus token, and
    ``seq_pos`` advances only by the accepted length, so rejected rows'
    KV writes sit past every visible position and the next append
    overwrites them.  Accepted lengths vary per slot; shapes never do.

Host plane: ONE device->host readback per step phase — the decode
harvest reads the sampled token vector once, and a step that completes
prefills reads their batched first tokens once (all prefill dispatches
stay async until then).  Admission, radix-tree matching, eviction,
eos/length bookkeeping and metrics all run on host ints the engine
already holds.

Per-slot sampling reuses ``generation._filter_top_p`` directly (its
threshold broadcasts over rows) and generalises ``_filter_top_k`` to a
per-row traced k via rank masking (``_filter_top_k_rows`` — the static-k
form cannot vary k within one compiled step).  Each slot draws from its
OWN PRNG key with the same split discipline as ``generate``, so a
single-request engine run reproduces ``generate(seed=...)`` token for
token, sampling included.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import _filter_top_p
from .aot import AOTStoreError, engine_aot_context, aot_fingerprint
from .errors import EngineStalledError, RequestRejected
from .health import (DegradationLadder, EngineHealth,
                     FaultToleranceConfig)
from .kv_pool import BlockPool, KVPool
from .metrics import ServingMetrics
from .prefix_cache import MatchResult, PrefixCache
from .scheduler import Request, Scheduler

__all__ = ["EngineCore", "sample_rows", "finite_or_sentinel",
           "NONFINITE_SENTINEL"]

# graftprog (tools/analysis/compile_surface.py) entry-point marker: the
# engine core is a registered compile-surface root — every jit program
# it can build must appear on the static manifest.  Pure data, read by
# the AST analysis only; zero runtime effect.
__compile_surface_roots__ = ("EngineCore",)

# graftmem (tools/analysis/memory.py) byte declarations: the engine
# plane's persistent device state OUTSIDE the derived pool slabs, as
# closed-form byte formulas over capacity fields.  ``row_state`` legs
# are the per-slot decode vectors ``_build_device_plane`` allocates
# (last token i32, PRNG key pair u32x2, sampling params bool+f32+i32+f32,
# logit mask bool[vocab]); ``staging`` is the single-slot prefill cache
# (per-layer k+v at the model dtype).  Pure data, read by the AST
# analysis and pinned against runtime measurement by
# tests/test_zz_memory_surface.py; zero runtime effect.
__memory_bytes__ = {
    "row_state._last_tok": "4 * num_slots",
    "row_state._keys": "8 * num_slots",
    "row_state._sampling_dev": "13 * num_slots",
    "row_state._mask_dev": "num_slots * vocab_size",
    "staging": "2 * num_layers * max_seq * kv_heads * head_dim * itemsize",
}

# token-readback encoding of the device-side health check: a decode row
# whose logits hold a non-finite value reads back as this instead of a
# token id (ids are always >= 0, so the sentinel is unambiguous) — the
# watchdog detects poisoned steps without adding a second device sync
NONFINITE_SENTINEL = -1


def finite_or_sentinel(logits, toks):
    """Encode per-row logits health into the sampled-token vector:
    ``toks[r]`` when ``logits[r]`` is all-finite, else
    :data:`NONFINITE_SENTINEL`.  Runs inside the decode program (and on
    the prefill first-token path), so non-finite detection rides the
    step's existing single readback."""
    ok = jnp.all(jnp.isfinite(logits), axis=-1)
    return jnp.where(ok, toks, NONFINITE_SENTINEL)


def _filter_top_k_rows(logits, top_k):
    """Per-row top-k: keep each row's ``top_k[r]`` highest logits
    (``top_k[r] == 0`` keeps the whole row).  Rank masking — argsort of
    the descending argsort — matches ``generation._filter_top_k`` for
    distinct values and resolves ties by vocab order (the stable-sort
    winner), which is also what argmax picks for k=1."""
    order = jnp.argsort(-logits, axis=-1)
    rank = jnp.argsort(order, axis=-1)
    k = jnp.asarray(top_k, jnp.int32)[:, None]
    keep = jnp.where(k > 0, rank < k, True)
    return jnp.where(keep, logits, -jnp.inf)


def sample_rows(keys, logits, do_sample, temperature, top_k, top_p,
                mask=None):
    """Per-row token selection over ``logits [rows, vocab]``.

    ``do_sample [rows] bool`` picks greedy argmax vs sampling per row;
    sampling rows apply ``temperature -> top_k -> top_p`` (the exact
    pipeline of ``generation.generate``) and draw from their OWN key row
    of ``keys [rows, key_dim]``, so one request's randomness never
    depends on its slot neighbours.

    ``mask [rows, vocab] bool`` (constrained decoding) bans False
    columns BEFORE everything — greedy argmax and the filter pipeline
    both see ``-inf`` there, so a constrained row renormalizes over its
    allowed set exactly like rejection-free constrained sampling.  The
    mask is a traced operand of the existing decode/verify programs:
    unconstrained rows pass all-True and the program set never grows."""
    logits = logits.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    greedy_tok = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits / temp[:, None]
    filtered = _filter_top_k_rows(scaled, top_k)
    p = jnp.asarray(top_p, jnp.float32)[:, None]
    # rows with top_p == 1.0 skip the nucleus filter EXACTLY, matching
    # generate()'s static skip; filtered rows take the nucleus lane
    filtered = jnp.where(p >= 1.0, filtered, _filter_top_p(filtered, p))
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(jnp.asarray(do_sample, bool), sampled, greedy_tok)


def _verify_tail(logits, drafts, draft_len, keys, do_sample, temperature,
                 top_k, top_p, mask, spec_k):
    """Matched-sampling acceptance over one verify window (runs inside
    the jitted verify program, after the model produced ``logits
    [rows, spec_k+1, vocab]``).

    The Python loop unrolls the EXACT per-token chain sequential decode
    runs — one ``jax.random.split`` per emitted token per slot, sample
    from the split's second half, carry the first — so position t's
    sample is identical to what the t-th sequential decode step would
    have drawn.  A slot's accepted length is its longest draft prefix
    matching those samples (``cumprod`` of the running match), and the
    committed tokens ARE the samples: greedy AND seeded runs are
    token-for-token identical to non-speculative decode by
    construction, and for temperature sampling the emitted tokens are
    literally draws from the sequential target distribution
    (rejection-sampling-correct with an exact-match acceptance rule).

    Each position is sentinel-encoded through ``finite_or_sentinel``
    first; the sentinel (-1) never equals a draft id (>= 0), so a
    poisoned position terminates acceptance by itself — at most ONE
    sentinel (the bonus slot) ever reaches the host, where the harvest
    fails the request exactly as sequential decode would have.

    Returns ``(committed [rows, spec_k+1] int32, accepted [rows] int32,
    new_keys [rows, ...])`` with ``new_keys`` the key-chain entry after
    ``accepted+1`` splits — the key sequential decode would hold."""
    carry = keys
    samples = []
    carries = [carry]
    for t in range(spec_k + 1):
        split = jax.vmap(lambda kk: jax.random.split(kk, 2))(carry)
        tok = sample_rows(split[:, 1], logits[:, t], do_sample,
                          temperature, top_k, top_p, mask=mask)
        tok = finite_or_sentinel(logits[:, t], tok)
        samples.append(tok.astype(jnp.int32))
        carry = split[:, 0]
        carries.append(carry)
    committed = jnp.stack(samples, axis=1)        # [rows, K+1]
    key_chain = jnp.stack(carries, axis=1)        # [rows, K+2, ...]
    if spec_k:
        valid = jnp.arange(spec_k)[None, :] < draft_len[:, None]
        match = (committed[:, :spec_k] == drafts) & valid
        accepted = jnp.sum(
            jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    else:
        accepted = jnp.zeros(committed.shape[:1], jnp.int32)
    new_keys = jax.vmap(lambda kc, a: kc[a])(key_chain, accepted + 1)
    return committed, accepted, new_keys


class _Slot:
    """Host mirror of one pool slot's request progress."""

    __slots__ = ("req", "pos", "match", "draft", "allowed")

    def __init__(self, req: Request, prompt_len: int,
                 match: Optional[MatchResult] = None,
                 draft=None, allowed=None):
        self.req = req
        self.pos = prompt_len       # cache length == next write offset
        self.match = match          # pinned radix-cache path, if any
        self.draft = draft          # per-request NGramDraftTable (spec)
        self.allowed = allowed      # frozenset of allowed token ids


class _Prefill:
    """A request mid-prefill: its slot is allocated, its context grows in
    a per-request staging cache (per-layer [1, max_seq] k/v rows seeded
    from the radix cache's matched blocks), and the scheduler's chunk
    plan drives one decode_step append per chunk."""

    __slots__ = ("req", "slot", "ks", "vs", "plan", "next_chunk", "match",
                 "last_logits")

    def __init__(self, req: Request, slot: int, ks, vs, plan,
                 match: Optional[MatchResult]):
        self.req = req
        self.slot = slot
        self.ks = ks                # staging caches, threaded per chunk
        self.vs = vs
        self.plan = plan            # [(offset, width, valid), ...]
        self.next_chunk = 0
        self.match = match
        self.last_logits = None     # final chunk's last-token logits

    @property
    def done(self) -> bool:
        return self.next_chunk >= len(self.plan)


class EngineCore:
    """Owns the pool, the radix prefix cache, the per-slot device state
    and the compiled step functions.  The public request/streaming
    surface lives in ``serving.api.ServingEngine``."""

    def __init__(self, model, num_slots: int = 8,
                 max_seq: Optional[int] = None,
                 min_bucket: int = 16,
                 max_prefills_per_step: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_prefill_tokens_per_step: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 block_len: int = 16,
                 prefix_blocks: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None,
                 fused_decode: bool = False,
                 fault_tolerance: Optional[FaultToleranceConfig] = None,
                 faults=None,
                 max_queue: Optional[int] = None,
                 tensor_parallel: int = 1,
                 collective_fusion: bool = True,
                 journal=None,
                 aot_store=None,
                 spec_k: int = 0):
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if prefill_chunk is not None and prefill_chunk < min_bucket:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must be >= min_bucket "
                f"{min_bucket}")
        if max_prefill_tokens_per_step is not None \
                and max_prefill_tokens_per_step < 1:
            raise ValueError("max_prefill_tokens_per_step must be >= 1")
        if enable_prefix_cache and block_len < 1:
            raise ValueError("block_len must be >= 1")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        self.model = model
        self.num_slots = num_slots
        self.prefill_chunk = prefill_chunk
        self.max_prefill_tokens_per_step = max_prefill_tokens_per_step
        self.metrics = metrics or ServingMetrics()
        # ---- robustness plumbing (docs/serving.md "Fault tolerance"):
        # the watchdog (step retry/backoff, degradation ladder,
        # quarantine rebuild, circuit breaker) engages only with an
        # explicit fault_tolerance config — without one the engine
        # raises exactly as before, so callers that own recovery keep
        # their semantics.  Deadlines, cancel() and backpressure are
        # always available.
        self.faults = faults                    # serving/faults.py hook
        # durable request journal (serving/journal.py, docs/serving.md
        # "Crash recovery"): submit records are written by the API
        # facade, terminal records by _finalize, and the per-step
        # delivered high-water marks batch at the END of the step —
        # every site guards `if journal is None` (the faults pattern),
        # so a journal-less engine pays nothing and compiles nothing new
        self.journal = journal
        self._journal_hwm: Dict[int, int] = {}
        # zero-cold-start (docs/serving.md "Zero cold start"): with an
        # attached AOT store the engine LOADS its compiled-program set
        # instead of tracing it — every site guards `if aot_store is
        # None` / on the loaded-handle dicts, so a store-less engine
        # pays nothing and compiles exactly as before.  _warm_buckets
        # (the committed chunk-width set) is derived after the
        # scheduler exists; _attach_aot runs after the decode path
        # resolves, and again from every _build_device_plane rebuild.
        self.aot_store = aot_store
        self.aot_status: Optional[str] = None
        self._warm_buckets: Optional[frozenset] = None
        self._aot_prefill: Dict[int, Callable] = {}
        self.fault_tolerant = fault_tolerance is not None
        self.ft = fault_tolerance if fault_tolerance is not None \
            else FaultToleranceConfig()
        self.health = EngineHealth(self.ft)
        self.ladder = DegradationLadder(self.ft.ladder_threshold)
        self.prefix_bypass = False              # ladder: cache disabled
        # ---- speculative decoding (docs/serving.md "Speculative
        # decoding"): spec_k > 0 arms the draft/verify path — per-slot
        # n-gram drafts (serving/spec.py) verified by ONE batched
        # [num_slots, spec_k+1] program.  Static legality resolves with
        # the decode path (_resolve_decode_path -> spec_on /
        # spec_fallback_reason); spec_bypass is the ladder's runtime
        # kill switch (a spec_verify fault ladder disables speculation
        # and the engine keeps serving one token per step).
        self.spec_k = spec_k
        self.spec_on = False
        self.spec_fallback_reason: Optional[str] = None
        self.spec_bypass = False                # ladder: spec disabled
        self.max_queue = max_queue if max_queue is not None \
            else self.ft.max_queue
        # monotone work marker: tokens emitted, admissions, prefill
        # chunks and terminal dispositions all bump it — the
        # run_until_complete stall detector watches it flatline
        self.progress_counter = 0
        self._deadlines_possible = False        # skip the per-step scan
        self._fault_phase: Optional[str] = None  # watchdog attribution
        # device-plane construction args, kept verbatim so a quarantine
        # rebuild (_build_device_plane) re-runs the same construction
        self._max_seq_arg = max_seq
        self._enable_prefix_cache = enable_prefix_cache
        self._block_len_arg = block_len
        self._prefix_blocks_arg = prefix_blocks
        # compiled-program trace counters: ONE decode fn + ONE prefill
        # fn whose jit cache is keyed by the [1, width] chunk shape (one
        # program per chunk width / pow2 bucket, nothing per length);
        # these (plus BlockPool.trace_counts for the two block-copy
        # programs) are what the compile-count guard tests assert on.
        # Engine-lifetime: a quarantine rebuild re-traces ON TOP of them
        # (exactly one more decode program, the same bucket set).
        self.trace_counts = {"prefill": 0, "decode": 0, "verify": 0}
        self._compile_seen: Dict[str, int] = {}
        # telemetry plumbing: the step index keys every phase span; the
        # step currently executing tags lazily-built programs' obs
        # events so they correlate with the surrounding serving.step span
        self._step_index = 0
        self._step_in_flight = 0
        # ---- tensor-parallel serving (docs/serving.md "Tensor-parallel
        # serving"): tp > 1 shards the WHOLE device plane over a 1-D
        # mesh — model weights Megatron-style, KV slot/block slabs on
        # the kv-head axis — and every compiled program becomes a
        # per-mesh SPMD program with its set size unchanged.  The decode
        # step additionally takes the fused compute-collective shard_map
        # path (serving/tp.py) when collective_fusion is on and the
        # model supports it; otherwise the composed GSPMD decode serves.
        if tensor_parallel < 1:
            raise ValueError(
                f"tensor_parallel must be >= 1, got {tensor_parallel}")
        self.tensor_parallel = tensor_parallel
        self.collective_fusion = collective_fusion
        self.mesh = None
        self._tp_program = None
        self._tp_program_path: Optional[str] = None
        self._tp_verify_program = None
        self._tp_verify_program_path: Optional[str] = None
        self.tp_fusion_reason: Optional[str] = None
        if tensor_parallel > 1:
            from . import tp as _tp
            # every construction-failure check runs BEFORE
            # shard_model_params mutates the caller's model in place: a
            # caller catching the ValueError and retrying at tp=1 must
            # get back an untouched single-device model, not one whose
            # weights were already laid out over a mesh
            cfg = model.cfg
            kv_heads = getattr(cfg, "kv_heads", None) or cfg.num_heads
            if kv_heads % tensor_parallel:
                raise ValueError(
                    f"kv_heads {kv_heads} must divide evenly over "
                    f"tensor_parallel {tensor_parallel} (the KV slot "
                    f"slabs partition on the kv-head axis)")
            self.mesh = _tp.build_serving_mesh(tensor_parallel)
            # GSPMD layout for the whole program set: prefill chunks,
            # staging init, gather/scatter, adopt and the sampling tail
            # all compile against the sharded weights
            _tp.shard_model_params(model, self.mesh)
        self.metrics.set_tp_degree(tensor_parallel)
        self._build_device_plane()
        self.scheduler = Scheduler(num_slots, self.pool.max_seq,
                                   min_bucket=min_bucket,
                                   max_prefills_per_step=max_prefills_per_step)
        # fused decode-block path (kernels/decode_block.py): opt-in flag,
        # resolved STATICALLY here — legality (shape/dtype/VMEM plan) and
        # routing never depend on runtime values, so the decode program
        # set stays {chunk} + buckets + ONE decode either way.  The
        # resolution lands in the decode_block obs event at compile time.
        self.fused_decode = fused_decode
        self.decode_path, self.decode_fallback_reason = \
            self._resolve_decode_path()
        # the committed bucket set is pinned ONCE, at construction —
        # the AOT builder enumerates the same set from an identically
        # configured engine, and _run_chunk's drift guard holds every
        # later plan width against it (ladder degradations only ever
        # shrink the reachable set, never escape it)
        self._warm_buckets = frozenset(self.warm_buckets())
        if self.aot_store is not None:
            self._attach_aot()

    def warm_buckets(self) -> Tuple[int, ...]:
        """The COMMITTED prefill chunk-width set: every width
        ``Scheduler.chunk_plan`` can emit for THIS configuration, over
        every reachable plan start (0, any block-aligned radix-cache
        match, and each chunk-stride position past those) — for both
        the chunked ladder rung and the chunking-disabled one, since
        the degradation ladder can drop ``prefill_chunk`` mid-life.
        This is the contract surface between the AOT builder and the
        runtime: the builder exports exactly one prefill program per
        width here, and ``_run_chunk`` raises (never silently traces)
        on a width outside it while a store is attached."""
        max_seq = self.pool.max_seq
        mb = max(self.scheduler.min_bucket, 1)
        chunk = self.prefill_chunk
        starts = {0}
        if self.block_pool is not None:
            starts.update(range(0, max_seq, self.block_pool.block_len))
        positions = set(starts)
        if chunk is not None:
            for s in starts:
                positions.update(range(s, max_seq, chunk))
        widths = set()
        for pos in positions:
            cap = max_seq - pos
            if cap < 1:
                continue
            # bucket_length values are {mb * 2^k} capped at the row
            # remainder — enumerate the ladder once per start
            b = mb
            while True:
                widths.add(min(b, cap))
                if chunk is not None:
                    widths.add(min(b, cap, chunk))
                if b >= cap:
                    break
                b *= 2
        if chunk is not None:
            widths.add(chunk)
        return tuple(sorted(widths))

    def _attach_aot(self) -> None:
        """Warm-load the compiled-program set from the attached store:
        one prefill per committed bucket width, gather + scatter into
        the block pool, the ONE decode at the resolved path.  Any miss
        (fingerprint skew, absent leg) or failed load (corrupt
        artifact, injected fault) degrades THAT program to
        trace-on-demand with an ``aot_miss``/``aot_fallback`` event —
        never a crash.  A bucket-set disagreement under a MATCHING
        fingerprint is different: builder and runtime no longer agree
        on the committed widths, the contract itself broke, and the
        engine refuses loudly."""
        store = self.aot_store
        t0 = time.perf_counter()
        self.aot_status = None
        self._aot_prefill = {}
        fp = aot_fingerprint(engine_aot_context(self))
        if fp != store.fingerprint:
            self.aot_status = "skew"
            self.metrics.on_aot_miss(
                "store", f"fingerprint skew: engine {fp[:12]}, store "
                         f"{store.fingerprint[:12]}")
            return
        committed = tuple(sorted(self._warm_buckets)) \
            if self._warm_buckets is not None \
            else self.warm_buckets()
        if tuple(store.widths) != tuple(committed):
            raise AOTStoreError(
                f"committed bucket drift under a matching fingerprint: "
                f"store built for widths {list(store.widths)}, runtime "
                f"enumerates {list(committed)} — builder and engine "
                f"disagree on warm_buckets()")
        wanted = 0
        loads = 0
        for w in committed:
            wanted += 1
            fn = self._aot_load(f"prefill:w{w}", donate=(0, 1))
            if fn is not None:
                self._aot_prefill[w] = fn
                loads += 1
        if self._aot_prefill:
            self._prefill_fn = self._make_aot_prefill_dispatch()
        if self.block_pool is not None:
            wanted += 2
            fn = self._aot_load("gather")
            if fn is not None:
                self.block_pool._load_fn = fn
                loads += 1
            fn = self._aot_load("scatter", donate=(0, 1))
            if fn is not None:
                self.block_pool._store_fn = fn
                loads += 1
        wanted += 1
        fn = self._aot_load(f"decode:{self.decode_path}",
                            donate=(0, 1))
        if fn is not None:
            # observability parity with the traced build: the
            # decode_block event still records which path this
            # engine's single decode program runs
            self.metrics.on_decode_block(
                active=self.decode_path in ("fused", "tp_fused_block"),
                reason=None if not self.fused_decode
                else self.decode_fallback_reason,
                step=self._step_in_flight,
                tp=self.tensor_parallel)
            self._decode_fn = fn
            loads += 1
        if self.spec_on:
            wanted += 1
            fn = self._aot_load(f"verify:{self.decode_path}",
                                donate=(0, 1))
            if fn is not None:
                self._verify_fn = fn
                loads += 1
        self.aot_status = "warm" if loads == wanted else \
            ("partial" if loads else "empty")
        if loads:
            self.metrics.on_aot_load(loads, time.perf_counter() - t0,
                                     build_s=store.build_seconds)

    def _aot_load(self, name: str,
                  donate: Tuple[int, ...] = ()) -> Optional[Callable]:
        """Load ONE program from the store, or None with the
        degradation event recorded (the caller then leaves the traced
        lazy-build path in place)."""
        store = self.aot_store
        if store is None:
            return None
        if not store.has(name):
            self.metrics.on_aot_miss(name, "not in store")
            return None
        try:
            if self.faults is not None:
                self.faults.fire("aot_load")
            return store.load_call(name, donate=donate, mesh=self.mesh)
        except Exception as e:
            self.metrics.on_aot_fallback(name, repr(e))
            return None

    def _make_aot_prefill_dispatch(self) -> Callable:
        """A ``_prefill_fn``-shaped dispatcher over the warm-loaded
        per-width programs.  A committed width whose artifact failed to
        load falls back to ONE lazily traced prefill (jit re-keys it
        per width exactly as the cold path would)."""
        loaded = self._aot_prefill
        traced: Dict[str, Optional[Callable]] = {"fn": None}

        def prefill_dispatch(ks, vs, ids, pos, valid):
            fn = loaded.get(int(ids.shape[1]))
            if fn is None:
                if traced["fn"] is None:
                    self.metrics.on_aot_fallback(
                        f"prefill:w{int(ids.shape[1])}",
                        "width artifact unavailable; tracing")
                    traced["fn"] = self._build_prefill_fn()
                fn = traced["fn"]
            return fn(ks, vs, ids, pos, valid)

        return prefill_dispatch

    def _build_device_plane(self) -> None:
        """Construct (or, on quarantine, RECONSTRUCT) everything that
        lives on the device or mirrors it: the KV pools, the prefix
        cache, per-slot row state and the compiled-program handles.  The
        scheduler, metrics, health state and queue are deliberately NOT
        touched — a rebuild must preserve queued work and telemetry.
        Fresh handles mean the jit wrappers re-trace on next use; the
        program SET stays {chunk} + buckets + ONE decode (pinned by the
        chaos suite's post-quarantine compile test)."""
        model, num_slots = self.model, self.num_slots
        self.pool = KVPool.create(model, num_slots, self._max_seq_arg,
                                  mesh=self.mesh)
        self.pool.faults = self.faults
        self.prefix_cache: Optional[PrefixCache] = None
        self.block_pool: Optional[BlockPool] = None
        # once the degradation ladder bypassed the cache, a quarantine
        # rebuild must not re-allocate its block slab: _cache_active
        # guarantees nothing would ever read or write it again
        if self._enable_prefix_cache and not self.prefix_bypass:
            block_len = self._block_len_arg
            # block_len must tile the slot row; shrink to the largest
            # pow2 divisor of max_seq when the requested size doesn't
            # (pow2 max_seqs — the common case — keep a pow2 request
            # verbatim).  Round DOWN to a pow2 first: halving a non-pow2
            # like 12 would otherwise walk 12->6->3->1 past the perfectly
            # good 8 and quietly build a per-token tree.
            block_len = 1 << (block_len.bit_length() - 1)
            while block_len > 1 and self.pool.max_seq % block_len:
                block_len //= 2
            # default pool size: as many blocks as the slot pool has rows
            # of context — a second slab the size of the first
            nb = self._prefix_blocks_arg \
                if self._prefix_blocks_arg is not None else \
                num_slots * (self.pool.max_seq // block_len)
            self.block_pool = BlockPool.create(model, nb, block_len,
                                               self.pool.max_seq,
                                               mesh=self.mesh)
            self.block_pool.faults = self.faults
            self.prefix_cache = PrefixCache(self.block_pool)
            self.prefix_cache.faults = self.faults
            # evictions land on THIS engine's timeline lane, not the
            # tracer's default lane 0 (another engine's, under sharing)
            self.prefix_cache.on_event = functools.partial(
                self.metrics.tracer.event, lane=self.metrics.engine_lane)
        self._slots: Dict[int, _Slot] = {}
        self._prefills: List[_Prefill] = []      # FCFS, mid-prefill
        # per-slot device row state (fixed [num_slots] shapes)
        self._last_tok = jnp.zeros((num_slots,), jnp.int32)
        key0 = jax.random.PRNGKey(0)
        self._keys = jnp.tile(key0[None], (num_slots,) + (1,) * key0.ndim)
        # per-slot sampling params: host numpy mirrors, re-uploaded to a
        # cached device copy only when admission/eviction dirties them
        # (values are traced row data — changing them never recompiles)
        self._do_sample = np.zeros((num_slots,), bool)
        self._temperature = np.ones((num_slots,), np.float32)
        self._top_k = np.zeros((num_slots,), np.int32)
        self._top_p = np.ones((num_slots,), np.float32)
        self._sampling_dev: Optional[Tuple] = None
        # per-slot allowed-token mask (constrained decoding): host rows
        # dirtied on admission/release, lazily re-uploaded like the
        # sampling params — all-True rows are unconstrained, and the
        # mask is traced row data in the SAME decode/verify programs
        self._mask_host = np.ones(
            (num_slots, int(model.cfg.vocab_size)), bool)
        self._mask_dev = None
        self._decode_fn = None
        self._verify_fn = None
        self._prefill_fn: Optional[Callable] = None
        self._staging_init_fn: Optional[Callable] = None
        # a rebuilt BlockPool's trace counters restart at zero: drop the
        # stale baseline so its re-traces still emit compile events
        self._compile_seen = {k: v for k, v in self._compile_seen.items()
                              if not k.startswith("block_")}
        # quarantine: the rebuilt plane re-loads from artifacts instead
        # of re-tracing (the first construction-time call runs from
        # __init__ once the decode path is resolved; _warm_buckets is
        # still None here on that first pass)
        if self.aot_store is not None and self._warm_buckets is not None:
            self._attach_aot()

    def _lane(self, req: Request) -> int:
        """Tracer lane for one request's lifecycle spans (the engine's
        own step-phase timeline sits on ``metrics.engine_lane``; lanes
        are per-engine blocks, so engines sharing a tracer never
        collide)."""
        return self.metrics.request_lane(req.request_id)

    # ----------------------------------------------------------- prefill
    def _build_prefill_fn(self) -> Callable:
        model = self.model

        def prefill(ks, vs, ids, pos, valid):
            self.trace_counts["prefill"] += 1  # trace-time side effect
            caches = [(k, v, pos) for k, v in zip(ks, vs)]
            logits, caches = model.decode_step(ids, caches, pos)
            last = jnp.take_along_axis(
                logits, (valid - 1)[None, None, None], axis=1)[0, 0]
            return (last.astype(jnp.float32),
                    [c[0] for c in caches], [c[1] for c in caches])

        # donating the staging rows threads them chunk to chunk in place
        return jax.jit(prefill, donate_argnums=(0, 1))

    def _prefill_cost(self, req: Request) -> int:
        """Tokens of prefill work admitting ``req`` costs THIS step: the
        width of its first chunk, after the radix-cache match shrinks the
        suffix.  This is what the scheduler's head-of-line budget check
        sees — a long-prompt head with a long cached prefix is cheap."""
        matched = self.prefix_cache.match_length(req.prompt) \
            if self._cache_active else 0
        plan = self.scheduler.chunk_plan(matched, req.prompt_len,
                                         self.prefill_chunk)
        return plan[0][1]

    @property
    def _cache_active(self) -> bool:
        """Prefix cache exists AND the degradation ladder has not
        bypassed it."""
        return self.prefix_cache is not None and not self.prefix_bypass

    def prefix_probe(self, prompt) -> int:
        """Longest radix-cached prefix of ``prompt`` in TOKENS, without
        admitting, pinning, or touching the device — a pure host walk of
        the radix tree (``PrefixCache.match_length``).  This is the
        replica-affinity signal the fleet router routes on: the replica
        whose cache already holds the longest prefix serves the request
        with the least recompute.  0 when the cache is off, bypassed by
        the degradation ladder, or simply cold."""
        if not self._cache_active:
            return 0
        return self.prefix_cache.match_length(prompt)

    # ------------------------------------------------ fleet KV handoff
    # The disaggregated fleet (docs/serving.md "Disaggregated fleet")
    # moves a finished prompt's radix blocks between replicas through
    # these two halves.  Both ride the EXISTING compiled surface: the
    # export is the prefix cache's one gather program, the adopt is the
    # slot-adopt copy + the one scatter program — the handoff adds zero
    # new compiled programs (pinned by the disagg chaos suite).

    def export_prompt_kv(self, prompt) -> Optional[MatchResult]:
        """PREFILL-side half: pin ``prompt``'s cached block path so the
        transfer window cannot lose it to LRU eviction.  Returns the
        pinned :class:`MatchResult` (``tokens == 0`` when nothing is
        cached), or None when the cache is off/bypassed.  The caller
        (serving/handoff.py) MUST hand the result back to
        :meth:`release_export` on every path — commit or abort."""
        if not self._cache_active:
            return None
        return self.prefix_cache.match(prompt, count_stats=False)

    def _mesh_scope(self):
        """The mesh context the engine's handoff copies dispatch under
        (a no-op scope on single-chip engines) — the same push
        ``_step_impl`` performs for the step programs."""
        if self.mesh is not None:
            return self.mesh
        return contextlib.nullcontext()

    def export_gather(self, match: MatchResult):
        """Read the pinned blocks into per-layer ``[1, max_seq, h, d]``
        staging rows via THE gather program (``BlockPool.load_row``)."""
        with self._mesh_scope():
            return self.prefix_cache.load_staging(match)

    def release_export(self, match: Optional[MatchResult]) -> None:
        """Unpin an export (idempotent — ``PrefixCache.release``).  A
        quarantine rebuild may have dropped the cache entirely
        (``prefix_cache = None`` under ladder bypass); the pinned nodes
        then belong to a discarded tree and nothing reads their
        refcounts again, so the release is a safe no-op — it must not
        crash the handoff's abort path."""
        if match is not None and self.prefix_cache is not None:
            self.prefix_cache.release(match)

    def adopt_prompt_kv(self, prompt, ks, vs, tokens: int,
                        faults=None) -> int:
        """DECODE-side half: land ``tokens`` transferred prompt tokens
        (staging rows ``ks``/``vs`` from the source's
        :meth:`export_gather`) in THIS engine's radix cache.  The rows
        stage through a transient pool slot — the scatter program's only
        legal source — which is freed again on every path, so the
        transfer can never leak a slot.  Returns the number of new
        blocks written (0: cache off/bypassed, or everything already
        cached here).  Raises when no slot is free — the caller gates on
        ``pool.free_slots`` and defers.  ``faults`` is the ROUTER-level
        injector: ``handoff_scatter`` fires after the slot claim, so
        the chaos suite proves the try/finally unwinding for real."""
        if not self._cache_active or tokens < self.block_pool.block_len:
            return 0
        slot = self.pool.alloc()
        try:
            if faults is not None:
                faults.fire("handoff_scatter")
            with self._mesh_scope():
                self.pool.adopt(slot, list(zip(ks, vs)), tokens,
                                set_pos=False)
                return self.prefix_cache.insert(
                    np.asarray(prompt)[:tokens], self.pool, slot)
        finally:
            self.pool.free(slot)

    def _contained_cache_fault(self, match: Optional[MatchResult],
                               exc: Exception) -> None:
        """A prefix-cache operation raised under the watchdog: unpin
        whatever was matched, count the fault toward the ladder (which
        bypasses the cache entirely at threshold) and let the admission
        continue as a plain cache miss — the cache is an optimization,
        never a correctness dependency."""
        if match is not None:
            self.prefix_cache.release(match)
        self._subsystem_fault("prefix_cache", exc)

    def _begin_prefill(self, req: Request) -> None:
        """Claim a slot, match + pin the longest cached prefix, seed the
        staging cache from its block rows (one gather program), and queue
        the suffix's chunk plan.  No model FLOPs run here.  The slot and
        the pinned radix path are returned to their pools if anything
        between claim and placement raises — admission failure must not
        bleed capacity (resource-lifecycle rule)."""
        t_admit = time.perf_counter()
        slot = self.pool.alloc()
        match = None
        try:
            matched = 0
            t_match0 = t_match1 = t_admit
            if self._cache_active:
                t_match0 = time.perf_counter()
                try:
                    match = self.prefix_cache.match(req.prompt)
                    matched = match.tokens
                except Exception as e:
                    if not self.fault_tolerant:
                        raise
                    self._contained_cache_fault(match, e)
                    match, matched = None, 0
                t_match1 = time.perf_counter()
            t_gather0 = time.perf_counter()
            if matched:
                try:
                    ks, vs = self.prefix_cache.load_staging(match)
                except Exception as e:
                    if not self.fault_tolerant:
                        raise
                    # degrade THIS admission to a miss (fresh staging,
                    # full-prompt prefill) and keep serving
                    self._contained_cache_fault(match, e)
                    match, matched = None, 0
            if not matched:
                # ONE compiled zero-staging builder instead of 2*num_layers
                # eager jnp.zeros dispatches per miss admission
                if self._staging_init_fn is None:
                    model, max_seq = self.model, self.pool.max_seq

                    def fresh_staging():
                        caches = model.init_cache(1, max_seq)
                        return ([c[0] for c in caches],
                                [c[1] for c in caches])

                    self._staging_init_fn = jax.jit(fresh_staging)
                ks, vs = self._staging_init_fn()
            t_gather1 = time.perf_counter()
            plan = self.scheduler.chunk_plan(matched, req.prompt_len,
                                             self.prefill_chunk)
            self.scheduler.place(req, slot)
            # hit/telemetry accounting only after placement: a failed
            # admission is requeued and retried, and must not count its
            # hit (or record its lifecycle spans) twice
            if matched:
                req.prefix_hit_tokens = matched
                self.metrics.on_prefix_hit(matched)
            req.admit_time = t_admit
            self.metrics.on_queue_wait(t_admit - req.arrival_time)
            self.metrics.on_gather(t_gather1 - t_gather0)
            tracer = self.metrics.tracer
            if tracer.enabled:
                lane = self._lane(req)
                tracer.set_lane_name(lane, f"request {req.request_id}")
                tracer.add_span("queued", lane, req.arrival_time, t_admit,
                                prompt_len=req.prompt_len)
                if self._cache_active:
                    tracer.add_span("prefix_match", lane, t_match0,
                                    t_match1, hit_tokens=matched)
                tracer.add_span("gather", lane, t_gather0, t_gather1,
                                hit=bool(matched))
            self._prefills.append(_Prefill(req, slot, ks, vs, plan, match))
            self.progress_counter += 1          # admission = progress
        except BaseException:
            if match is not None:
                self.prefix_cache.release(match)
            self.pool.free(slot)
            raise

    def _run_chunk(self, st: _Prefill) -> None:
        """Dispatch one prefill chunk of ``st`` (async — no readback)."""
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill_fn()
        off, width, valid = st.plan[st.next_chunk]
        if self.aot_store is not None and self._warm_buckets is not None \
                and width not in self._warm_buckets:
            # the committed-bucket contract (warm_buckets) broke: with
            # a store attached this must be LOUD, not a silent trace
            raise AOTStoreError(
                f"prefill width {width} is outside the committed "
                f"bucket set {sorted(self._warm_buckets)} — "
                f"warm_buckets()/chunk_plan drift")
        t0 = time.perf_counter()
        ids = np.zeros((1, width), np.int32)
        ids[0, :valid] = np.asarray(st.req.prompt[off:off + valid],
                                    np.int32)
        last_logits, st.ks, st.vs = self._prefill_fn(
            st.ks, st.vs, jnp.asarray(ids),
            jnp.asarray(off, jnp.int32), jnp.asarray(valid, jnp.int32))
        t1 = time.perf_counter()
        st.next_chunk += 1
        st.req.prefill_chunks += 1
        self.progress_counter += 1              # chunk ran = progress
        self.metrics.on_prefill_chunk(valid, seconds=t1 - t0)
        self.metrics.tracer.add_span(
            "prefill_chunk", self._lane(st.req), t0, t1,
            chunk=st.next_chunk - 1, width=width, tokens=valid)
        if st.done:
            st.last_logits = last_logits

    def _complete_prefill(self, st: _Prefill):
        """Final chunk done: sample the first token with the request's
        own key and adopt the staging row into the pool slot.  Returns
        ``(st, first_token_array)`` — the caller batches the readbacks
        (``_flush_staged``), and only THEN publishes the prompt blocks
        to the radix cache: the first token doubles as the device-side
        finiteness probe, and KV whose prefill produced non-finite
        logits must never be inserted where future admissions would
        copy it."""
        req, slot = st.req, st.slot
        key = jax.random.PRNGKey(req.sampling.seed)
        key, sub = jax.random.split(key)
        s = req.sampling
        allowed = None
        self._mask_host[slot] = True
        if req.allowed_tokens is not None:
            # constrained decoding: the per-slot vocab mask constrains
            # the FIRST token here and every later one inside the
            # decode/verify programs; the host set gates draft proposals
            allowed = frozenset(int(t) for t in req.allowed_tokens)
            self._mask_host[slot] = False
            self._mask_host[slot, np.asarray(req.allowed_tokens,
                                             np.int64)] = True
        self._mask_dev = None
        first = sample_rows(
            sub[None], st.last_logits[None],
            jnp.asarray([s.do_sample]),
            jnp.asarray([s.temperature], jnp.float32),
            jnp.asarray([s.top_k], jnp.int32),
            jnp.asarray([s.top_p], jnp.float32),
            mask=jnp.asarray(self._mask_host[slot][None]))
        first = finite_or_sentinel(st.last_logits[None], first)
        draft = None
        if self.spec_on:
            from .spec import NGramDraftTable
            draft = NGramDraftTable()
            draft.seed(req.prompt)
        self.pool.adopt(slot, list(zip(st.ks, st.vs)), req.prompt_len)
        self._slots[slot] = _Slot(req, req.prompt_len, match=st.match,
                                  draft=draft, allowed=allowed)
        self._last_tok = self._last_tok.at[slot].set(first[0])
        self._keys = self._keys.at[slot].set(key)
        self._do_sample[slot] = s.do_sample
        self._temperature[slot] = s.temperature
        self._top_k[slot] = s.top_k
        self._top_p[slot] = s.top_p
        self._sampling_dev = None
        self.metrics.on_prefill(req.prompt_len - req.prefix_hit_tokens)
        return st, first

    def _advance_one(self, st: _Prefill, staged: List) -> None:
        """Advance one mid-prefill request — to completion without
        chunking, by exactly one chunk with it — appending the completed
        ``(st, first_token)`` to ``staged``.  Under the watchdog, a
        prefill-execution fault is PRECISELY attributable (unlike a
        decode fault, which spans every slot): the implicated request is
        failed terminally and the engine keeps serving the rest."""
        try:
            if self.prefill_chunk is None:
                while not st.done:
                    self._run_chunk(st)
            else:
                self._run_chunk(st)
            if st.done:
                self._prefills.remove(st)
                staged.append(self._complete_prefill(st))
        except Exception as e:
            if not self.fault_tolerant:
                raise
            # the staging rows were donated into the raising dispatch —
            # this prefill's state is unrecoverable, the engine's isn't
            self._abort_prefill(st, "failed", f"prefill fault: {e!r}")
            if self.prefill_chunk is not None:
                self._subsystem_fault("chunked_prefill", e)
            else:
                self.metrics.on_fault("prefill", repr(e),
                                      step=self._step_in_flight)

    def _advance_prefills(self) -> int:
        """Run this step's prefill work.  Without chunking every pending
        prefill completes (the legacy admit-then-decode shape); with
        ``prefill_chunk`` set, exactly ONE chunk runs per step, so the
        per-step decode stall is bounded by one chunk regardless of how
        long the admitted prompt is.  Completed requests' first tokens
        come back in ONE batched readback.  Returns tokens emitted."""
        staged: List[Tuple[_Prefill, jax.Array]] = []
        try:
            if self.prefill_chunk is None:
                while self._prefills:
                    n = len(self._prefills)
                    self._advance_one(self._prefills[0], staged)
                    if len(self._prefills) >= n:
                        break    # defensive: no progress, stop looping
            elif self._prefills:
                self._advance_one(self._prefills[0], staged)
        finally:
            # even if a later prefill raised, tokens already staged must
            # be emitted — a sampled first token the host forgets would
            # silently desync the request from generate() parity
            emitted = self._flush_staged(staged)
        return emitted

    def _flush_staged(self, staged: List[Tuple[_Prefill, jax.Array]]) -> int:
        """THE batched first-token readback for this step's completed
        prefills, then per request: non-finite containment (fail the
        request, skip the radix insert — the poison must not be cached),
        the deferred prefix-cache insert, and the first-token emit."""
        if not staged:
            return 0
        toks = np.asarray(jnp.concatenate([f for _, f in staged]))
        emitted = 0
        flush_exc = None
        for (st, _), tok in zip(staged, toks):
            tok = int(tok)
            if tok == NONFINITE_SENTINEL:
                self.metrics.on_fault(
                    "nan_logits", "non-finite logits at prefill "
                    "completion", step=self._step_in_flight)
                self._finalize(st.req, "failed",
                               "non-finite logits at prefill completion")
                continue   # slot reclaimed by _evict_finished this step
            if self._cache_active:
                try:
                    self.prefix_cache.insert(st.req.prompt, self.pool,
                                             st.slot)
                except Exception as e:
                    if not self.fault_tolerant:
                        raise
                    # the insert is an optimization — count the fault
                    # (ladder may bypass the cache) and keep the request
                    self._subsystem_fault("prefix_cache", e)
            # same containment as the decode-harvest loop: these slots
            # were already adopted and their first tokens sampled — a
            # raise for one must not drop the others' first tokens
            try:
                self._emit(st.slot, tok, first_token=True)
            except Exception as e:
                self.metrics.on_fault("harvest", repr(e),
                                      step=self._step_in_flight)
                self._finalize(st.req, "failed",
                               f"token emit failed: {e!r}")
                if flush_exc is None:
                    flush_exc = e
                continue
            emitted += 1
        if flush_exc is not None and not self.fault_tolerant:
            raise flush_exc
        return emitted

    # ------------------------------------------------------------ decode
    def _resolve_decode_path(self):
        """Statically resolve the decode implementation for THIS
        engine's shapes: the ``fused_decode`` flag opts into the Pallas
        decode-block kernels, ``decode_block_route`` applies the
        routing policy (flags + measured win region), and the model's
        ``fused_decode_supported`` checks shape/dtype/VMEM legality.
        Under tensor parallelism the fallback chain gains a leg: the
        SHARDED Pallas decode block (``"tp_fused_block"``,
        kernels/decode_block_tp.py — entry/exit ring collectives riding
        the tile dots, in-kernel append on the local slab shard)
        engages when the flag opts in, ``collective_fusion`` is on (its
        rings ARE the fused collectives) and
        ``resolve_fused_decode(tp=...)`` passes the real legality
        (kv_heads/batch/ffn tiling, head alignment, per-shard VMEM
        plan); otherwise the composed compute-collective shard_map
        program (``"tp_fused"``, serving/tp.py) when legal, the
        composed GSPMD decode last — every rung keeps serving.  Returns
        ``(path, fallback_reason)``; reason is None when a fused-block
        path engages (or the flag is simply off).

        The SPECULATIVE leg resolves here too, statically:
        ``spec_on``/``spec_fallback_reason`` name why speculation is
        armed or not for this engine shape (never a runtime surprise —
        the per-step room gate and the ladder's ``spec_bypass`` are the
        only dynamic fallbacks, both named in ``decode_path_info``)."""
        from ..kernels.decode_block import resolve_fused_decode
        if self.spec_k == 0:
            self.spec_on = False
            self.spec_fallback_reason = \
                "spec_k=0 (speculation not requested)"
        elif self.pool.max_seq <= self.spec_k + 1:
            self.spec_on = False
            self.spec_fallback_reason = (
                f"max_seq {self.pool.max_seq} leaves no room for a "
                f"spec_k={self.spec_k} verify window")
        else:
            self.spec_on = True
            self.spec_fallback_reason = None
        if self.tensor_parallel > 1:
            reason = None
            if self.fused_decode:
                ok, reason = resolve_fused_decode(
                    self.model, batch=self.num_slots,
                    kv_len=self.pool.max_seq, tp=self.tensor_parallel)
                if ok and not self.collective_fusion:
                    ok, reason = False, ("collective_fusion disabled "
                                         "(the sharded block's "
                                         "entry/exit rings are fused "
                                         "collectives)")
                if ok:
                    self.tp_fusion_reason = None
                    return "tp_fused_block", None
            from . import tp as _tp
            ok, tp_reason = _tp.tp_decode_supported(
                self.model, self.tensor_parallel, self.num_slots) \
                if self.collective_fusion \
                else (False, "collective_fusion disabled")
            self.tp_fusion_reason = None if ok else tp_reason
            return ("tp_fused" if ok else "unfused"), reason
        if not self.fused_decode:
            return "unfused", None
        ok, reason = resolve_fused_decode(self.model,
                                          batch=self.num_slots,
                                          kv_len=self.pool.max_seq)
        return ("fused", None) if ok else ("unfused", reason)

    def _build_decode_fn(self) -> Callable:
        model = self.model
        fused = self.decode_path == "fused"
        # the discrete obs event marks WHICH path this engine's single
        # decode program compiled with (and why, on fallback) — traces
        # distinguish fused from unfused steps without diffing configs;
        # the tp dimension separates the sharded block from the tp=1
        # pair in a shared registry (glossary: docs/observability.md)
        self.metrics.on_decode_block(
            active=self.decode_path in ("fused", "tp_fused_block"),
            reason=None if not self.fused_decode
            else self.decode_fallback_reason,
            step=self._step_in_flight,
            tp=self.tensor_parallel)
        if self.decode_path in ("tp_fused", "tp_fused_block"):
            return self._build_tp_decode_fn()

        def decode(ks, vs, seq_pos, last_tok, keys, do_sample,
                   temperature, top_k, top_p, mask):
            self.trace_counts["decode"] += 1  # trace-time side effect
            caches = [(k, v, seq_pos) for k, v in zip(ks, vs)]
            step_fn = model.fused_decode_step if fused else \
                model.decode_step
            logits, caches = step_fn(last_tok[:, None], caches, seq_pos)
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            nxt = sample_rows(split[:, 1], logits[:, 0], do_sample,
                              temperature, top_k, top_p, mask=mask)
            # device-side health probe: a poisoned row reads back as the
            # sentinel through the step's EXISTING single readback (a
            # no-op on finite logits, so token parity is untouched)
            nxt = finite_or_sentinel(logits[:, 0], nxt)
            new_ks = [c[0] for c in caches]
            new_vs = [c[1] for c in caches]
            return (new_ks, new_vs, caches[0][2], nxt.astype(jnp.int32),
                    split[:, 0])

        # donating the KV slabs aliases them in place — pool memory stays
        # a single allocation across the whole serving run
        return jax.jit(decode, donate_argnums=(0, 1))

    def _build_tp_decode_fn(self) -> Callable:
        """The tensor-parallel fused compute-collective decode: ONE
        shard_map program (serving/tp.py) whose entry all-gathers ride
        the QKV/MLP-up dots and whose exit reduce-scatters ride the
        out-proj/MLP-down dots, then the SAME per-slot sampling tail as
        the composed path on the vocab-sharded logits (GSPMD partitions
        the argmax/top-k reductions).  On the ``tp_fused_block`` path
        the same program's layer bodies run the sharded Pallas
        decode-block kernels instead (kernels/decode_block_tp.py) —
        same signature, same donation, same single compiled decode
        program either way, so the compile-count pin is untouched.  The
        weight bundle survives quarantine rebuilds (it is never
        donated), so a rebuilt plane reuses it; a degradation-ladder
        path change invalidates the cached program (it is path-
        specific)."""
        from . import tp as _tp
        if self._tp_program is None \
                or self._tp_program_path != self.decode_path:
            self._tp_program = _tp.build_tp_decode_program(
                self.model, self.mesh, self.tensor_parallel,
                pallas_block=self.decode_path == "tp_fused_block",
                batch=self.num_slots, max_seq=self.pool.max_seq)
            self._tp_program_path = self.decode_path
        program = self._tp_program

        def decode(ks, vs, seq_pos, last_tok, keys, do_sample,
                   temperature, top_k, top_p, mask):
            self.trace_counts["decode"] += 1  # trace-time side effect
            logits, new_ks, new_vs, new_pos = program(
                ks, vs, seq_pos, last_tok)
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            lg = logits[:, 0]
            nxt = sample_rows(split[:, 1], lg, do_sample,
                              temperature, top_k, top_p, mask=mask)
            nxt = finite_or_sentinel(lg, nxt)
            return (new_ks, new_vs, new_pos, nxt.astype(jnp.int32),
                    split[:, 0])

        return jax.jit(decode, donate_argnums=(0, 1))

    def _decode_dispatch(self) -> jax.Array:
        """ONE fixed-shape decode step over every slot; returns the
        sampled token vector STILL ON DEVICE — the caller performs the
        step's single host readback (step() times dispatch and readback
        as separate timeline phases)."""
        if self._decode_fn is None:
            # a degradation-ladder path change dropped the handle: try
            # the store's artifact for the NEW path first (a miss is a
            # recorded degradation event), trace only when it has none
            if self.aot_store is not None \
                    and self.aot_status not in (None, "skew"):
                self._decode_fn = self._aot_load(
                    f"decode:{self.decode_path}", donate=(0, 1))
            if self._decode_fn is None:
                self._decode_fn = self._build_decode_fn()
        if self._sampling_dev is None:
            self._sampling_dev = (jnp.asarray(self._do_sample),
                                  jnp.asarray(self._temperature),
                                  jnp.asarray(self._top_k),
                                  jnp.asarray(self._top_p))
        if self._mask_dev is None:
            self._mask_dev = jnp.asarray(self._mask_host)
        ks, vs, pos, nxt, self._keys = self._decode_fn(
            self.pool.ks, self.pool.vs, self.pool.seq_pos,
            self._last_tok, self._keys, *self._sampling_dev,
            self._mask_dev)
        self.pool.ks, self.pool.vs, self.pool.seq_pos = ks, vs, pos
        self._last_tok = nxt
        return nxt

    # ----------------------------------------- speculative decode (spec)
    def _build_verify_fn(self) -> Callable:
        """The ONE batched verify program of the speculative path
        (docs/serving.md "Speculative decoding"): fixed shapes
        ``[num_slots, spec_k+1]`` regardless of per-slot acceptance.

        The window runs ``model.decode_step`` at token width
        ``spec_k+1`` with per-slot positions — the SAME ragged
        discipline as decode (``cache_lens`` gives query t of a slot's
        window visibility up to ``pos+t``), so free and mid-prefill
        rows ride along as no-ops exactly as they do in decode.
        Acceptance is MATCHED SAMPLING (``_verify_tail``): the program
        replays the exact per-token split/sample chain sequential
        decode would run over these logits, so the committed tokens ARE
        the sequential target's tokens — token-for-token parity, greedy
        and seeded, is structural rather than probabilistic.  KV of
        rejected positions is written (fixed shapes) but never becomes
        visible: ``seq_pos`` advances only by accepted+1, and the next
        append overwrites the stale tail."""
        model = self.model
        if self.decode_path in ("tp_fused", "tp_fused_block"):
            return self._build_tp_verify_fn()

        def verify(ks, vs, seq_pos, last_tok, keys, do_sample,
                   temperature, top_k, top_p, mask, drafts, draft_len):
            self.trace_counts["verify"] += 1  # trace-time side effect
            caches = [(k, v, seq_pos) for k, v in zip(ks, vs)]
            ids = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            logits, caches = model.decode_step(ids, caches, seq_pos)
            committed, accepted, new_keys = _verify_tail(
                logits, drafts, draft_len, keys, do_sample, temperature,
                top_k, top_p, mask, self.spec_k)
            new_last = jnp.take_along_axis(
                committed, accepted[:, None], axis=1)[:, 0]
            # the caches advanced the full window width — the ragged
            # truth is accepted+1, which also re-hides rejected KV
            new_pos = seq_pos + accepted + 1
            packed = jnp.concatenate([committed, accepted[:, None]],
                                     axis=1)
            new_ks = [c[0] for c in caches]
            new_vs = [c[1] for c in caches]
            return (new_ks, new_vs, new_pos,
                    new_last.astype(jnp.int32), packed, new_keys)

        return jax.jit(verify, donate_argnums=(0, 1))

    def _build_tp_verify_fn(self) -> Callable:
        """Tensor-parallel fused verify: the width-``spec_k+1`` member
        of the SAME shard_map family as the fused decode
        (tp.build_tp_verify_program — identical bundle layout and
        specs, the layer seam IS ``_tp_layer``), with the matched-
        sampling acceptance tail under GSPMD on the vocab-sharded
        logits inside the same jit.  The ``tp_fused_block`` path
        verifies through this program too (the Pallas block is a
        single-token kernel) and keeps its block for decode steps."""
        from . import tp as _tp
        if self._tp_verify_program is None \
                or self._tp_verify_program_path != self.decode_path:
            self._tp_verify_program = _tp.build_tp_verify_program(
                self.model, self.mesh, self.tensor_parallel,
                width=self.spec_k + 1)
            self._tp_verify_program_path = self.decode_path
        program = self._tp_verify_program

        def verify(ks, vs, seq_pos, last_tok, keys, do_sample,
                   temperature, top_k, top_p, mask, drafts, draft_len):
            self.trace_counts["verify"] += 1  # trace-time side effect
            ids = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            logits, new_ks, new_vs, _ = program(ks, vs, seq_pos, ids)
            committed, accepted, new_keys = _verify_tail(
                logits, drafts, draft_len, keys, do_sample, temperature,
                top_k, top_p, mask, self.spec_k)
            new_last = jnp.take_along_axis(
                committed, accepted[:, None], axis=1)[:, 0]
            new_pos = seq_pos + accepted + 1
            packed = jnp.concatenate([committed, accepted[:, None]],
                                     axis=1)
            return (new_ks, new_vs, new_pos,
                    new_last.astype(jnp.int32), packed, new_keys)

        return jax.jit(verify, donate_argnums=(0, 1))

    def _verify_dispatch(self, drafts: np.ndarray,
                         draft_len: np.ndarray) -> jax.Array:
        """ONE fixed-shape verify step over every slot; returns the
        packed ``[num_slots, spec_k+2]`` commit rows (each slot's
        sentinel-encoded window samples + its accepted draft length)
        STILL ON DEVICE — the caller performs the step's single host
        readback, exactly like decode."""
        if self._verify_fn is None:
            if self.aot_store is not None \
                    and self.aot_status not in (None, "skew"):
                self._verify_fn = self._aot_load(
                    f"verify:{self.decode_path}", donate=(0, 1))
            if self._verify_fn is None:
                self._verify_fn = self._build_verify_fn()
        if self._sampling_dev is None:
            self._sampling_dev = (jnp.asarray(self._do_sample),
                                  jnp.asarray(self._temperature),
                                  jnp.asarray(self._top_k),
                                  jnp.asarray(self._top_p))
        if self._mask_dev is None:
            self._mask_dev = jnp.asarray(self._mask_host)
        ks, vs, pos, nxt, packed, self._keys = self._verify_fn(
            self.pool.ks, self.pool.vs, self.pool.seq_pos,
            self._last_tok, self._keys, *self._sampling_dev,
            self._mask_dev, jnp.asarray(drafts), jnp.asarray(draft_len))
        self.pool.ks, self.pool.vs, self.pool.seq_pos = ks, vs, pos
        self._last_tok = nxt
        return packed

    def _propose_drafts(self):
        """Host draft phase: ask every active slot's n-gram table for up
        to ``spec_k`` tokens.  Returns ``(drafts [num_slots, spec_k],
        draft_len [num_slots], total_drafted)`` or None when this step
        should run the normal decode instead — speculation off/bypassed,
        nothing proposed anywhere, or ANY occupied slot within
        ``spec_k+1`` rows of its row end (``append_kv`` clamps a
        window's start at ``max_seq - width``, which would overwrite
        that row's valid KV — the whole step falls back rather than
        corrupt it; such a slot is about to hit max_seq anyway)."""
        if not self.spec_on or self.spec_bypass or not self._slots:
            return None
        k = self.spec_k
        limit = self.pool.max_seq - k - 1
        drafts = np.zeros((self.num_slots, k), np.int32)
        lens = np.zeros((self.num_slots,), np.int32)
        total = 0
        for slot, st in self._slots.items():
            if st.pos > limit:
                return None
            if st.draft is None or st.req.finished:
                continue
            toks = st.draft.propose(k, allowed=st.allowed)
            if toks:
                drafts[slot, :len(toks)] = toks
                lens[slot] = len(toks)
                total += len(toks)
        if total == 0:
            return None
        return drafts, lens, total

    # -------------------------------------------------------- step loop
    def step(self) -> int:
        """One engine iteration: admit (radix match + staging), advance
        prefill chunks, one decode step over all active slots, harvest
        tokens / evict finished.  Returns the number of requests still
        in flight (prefilling + running + queued).

        With ``fault_tolerance`` configured this is the WATCHDOG
        boundary: a step exception is caught, attributed (optional
        subsystem → degradation ladder; core → bounded exponential-
        backoff retry → quarantine rebuild), and never propagates — the
        recovery matrix is in docs/serving.md.  Without the config the
        engine raises exactly as before."""
        if not self.fault_tolerant:
            return self._step_impl()
        if self.health.circuit_open:
            # fail-fast mode: the breaker already failed all work and
            # submit() rejects — stepping is a no-op, never a rebuild
            return self.scheduler.active + self.scheduler.queue_depth
        try:
            out = self._step_impl()
        except Exception as e:
            return self._on_step_fault(e)
        self.health.on_step_ok()
        self._publish_health()
        return out

    def _step_impl(self) -> int:
        """``_step_body`` inside the mesh scope when tensor-parallel:
        the engine's jitted programs trace their bare-PartitionSpec
        sharding constraints (the models' ``_maybe_constraint`` calls)
        against the serving mesh, so GSPMD partitions every program the
        step dispatches.  Single-chip engines skip the push entirely."""
        if self.mesh is None:
            return self._step_body()
        with self.mesh:
            return self._step_body()

    def _step_body(self) -> int:
        """The raw step.  Telemetry rides the loop off the hot path: the
        step's phase breakdown (admission / prefill / decode dispatch /
        readback) lands as ``step.*`` spans on the engine lane +
        per-phase histograms, and trace-counter deltas / head-of-line
        skips / evictions become discrete events.  The per-slot token
        readback stays the step's ONLY device sync."""
        t0 = time.perf_counter()
        tracer = self.metrics.tracer
        step_i = self._step_index
        self._step_index += 1
        self._step_in_flight = step_i
        self._fault_phase = None
        skips_before = self.scheduler.total_head_skips
        faults = self.faults
        if faults is not None:
            armed = faults.check("slow_step")
            if armed is not None:
                self.metrics.on_fault(
                    "slow_step", f"injected {armed.seconds}s stall",
                    step=step_i)
                time.sleep(armed.seconds)
        ann = None
        if self.metrics.record_events:
            from ..profiler import RecordEvent
            ann = RecordEvent("serving.step")
            ann.begin()
        sp = tracer.begin_span("serving.step",
                               lane=self.metrics.engine_lane,
                               step=step_i)
        try:
            if self._deadlines_possible:
                self._expire_deadlines(time.perf_counter())
            admitted = self.scheduler.admit(
                self.pool.free_slots,
                token_budget=self.max_prefill_tokens_per_step,
                cost=self._prefill_cost)
            for i, (req, _) in enumerate(admitted):
                try:
                    self._begin_prefill(req)
                except BaseException:
                    # admission failure must not LOSE requests: the
                    # failing one and the rest of the popped batch go
                    # back to the queue head (their slots/pins were
                    # already returned)
                    self.scheduler.requeue_front(
                        [r for r, _ in admitted[i:]])
                    raise
            t_admit = time.perf_counter()
            new_tokens = self._advance_prefills()
            t_prefill = time.perf_counter()
            phases = [("admission", t0, t_admit),
                      ("prefill", t_admit, t_prefill)]
            if self._slots:
                if faults is not None:
                    armed = faults.check("nan_logits")
                    if armed is not None:
                        self._poison_slot(min(self._slots), step_i)
                # speculative draft phase (pure host, spec_on only):
                # None -> normal decode this step, else the batched
                # fixed-shape verify program commits up to spec_k+1
                # tokens per slot
                spec = self._propose_drafts()
                # decode faults cannot be pinned on one slot — the
                # watchdog attributes them to the decode path (ladder
                # candidate when fused or speculating, retry/quarantine
                # otherwise)
                if spec is not None:
                    self._fault_phase = "spec_verify"
                else:
                    self._fault_phase = "fused_decode" \
                        if self.decode_path in ("fused",
                                                "tp_fused_block") \
                        else "decode"
                if faults is not None:
                    faults.fire("step")
                    if spec is not None:
                        # fires BEFORE dispatch: nothing was mutated
                        # yet, so the ladder's retry step is clean
                        faults.fire("spec_verify")
                if spec is not None:
                    drafts, draft_len, drafted = spec
                    nxt = self._verify_dispatch(drafts, draft_len)
                else:
                    nxt = self._decode_dispatch()
                t_decode = time.perf_counter()
                toks = np.asarray(nxt)     # THE per-step device readback
                t_readback = time.perf_counter()
                self._fault_phase = None
                # the readback already advanced EVERY slot's device
                # state: a raise mid-loop (a user stream callback, an
                # emit bug) must not drop the LATER slots' tokens — on
                # the watchdog's retry they would silently skip one
                # token and desync from generate() parity.  Finish the
                # loop, fail the implicated request, re-raise only
                # outside the watchdog (inside it the containment is
                # already complete — no retry needed).
                harvest_exc = None
                accepted_total = 0
                for slot in sorted(self._slots):
                    # a stream callback may REENTRANTLY cancel/purge a
                    # sibling (first-of-N-wins clients): re-fetch, and
                    # skip slots that vanished mid-loop
                    st = self._slots.get(slot)
                    if st is None:
                        continue
                    try:
                        if spec is None:
                            new_tokens += self._harvest(slot,
                                                        int(toks[slot]))
                        else:
                            a = int(toks[slot, self.spec_k + 1])
                            accepted_total += a
                            new_tokens += self._harvest_window(
                                slot, toks[slot, :a + 1])
                    except Exception as e:
                        self.metrics.on_fault("harvest", repr(e),
                                              step=step_i)
                        self._finalize(st.req, "failed",
                                       f"token emit failed: {e!r}")
                        if harvest_exc is None:
                            harvest_exc = e
                if spec is not None:
                    self.metrics.on_spec(int(drafted), accepted_total)
                if harvest_exc is not None and not self.fault_tolerant:
                    raise harvest_exc
                # decode phases exist only on steps that decoded — a
                # prefill-only step must not feed 0.0 into their
                # histograms and fake slices into the timeline
                phases += [("decode_dispatch", t_prefill, t_decode),
                           ("readback", t_decode, t_readback)]
                if self.decode_path in ("fused", "tp_fused_block"):
                    # fused-path dispatch cost, separable from unfused
                    # runs in the same registry (glossary:
                    # kernel.decode_block_s, docs/observability.md)
                    self.metrics.on_decode_block_step(t_decode - t_prefill)
                if self.tensor_parallel > 1:
                    # the TP decode's dispatch+readback carries its
                    # fused entry/exit collectives — this histogram is
                    # the trace evidence for the collective-fusion path
                    # (glossary: serving.collective_s)
                    self.metrics.on_collective(t_readback - t_prefill)
            self._evict_finished()
            if self.journal is not None:
                self._journal_progress()
        finally:
            # a raised step must still close the span and the trace
            # annotation, or every later event nests inside a phantom
            # serving.step (resource-lifecycle rule: begin_span/end_span)
            tracer.end_span(sp)
            if ann is not None:
                ann.end()
        self._record_events(step_i, skips_before)
        self.metrics.record_step(
            active_slots=len(self._slots), num_slots=self.num_slots,
            queue_depth=self.scheduler.queue_depth,
            new_tokens=new_tokens,
            step_seconds=time.perf_counter() - t0,
            step_index=step_i,
            phases=phases)
        return self.scheduler.active + self.scheduler.queue_depth

    def _journal_progress(self) -> None:
        """Batch this step's delivered high-water marks into ONE journal
        record (host ints the harvest loop already produced — nothing
        here touches the device).  Runs at the end of the step, after
        eviction, so a request that finished this step is covered by its
        terminal record instead."""
        updates = {}
        for st in self._slots.values():
            rid, n = st.req.request_id, len(st.req.tokens)
            if n and self._journal_hwm.get(rid) != n:
                updates[rid] = self._journal_hwm[rid] = n
        self.journal.append_progress(updates)

    def _poison_slot(self, slot: int, step_i: int) -> None:
        """Chaos-only: overwrite position 0 of ``slot``'s layer-0 K row
        with NaN.  Decode attention propagates it into that slot's
        logits, the in-program finiteness probe encodes the sentinel,
        and the harvest fails exactly the implicated request — the
        honest end-to-end drive of the non-finite recovery path (the
        poisoned position is re-written wholesale by the next adopt)."""
        self.pool.ks[0] = self.pool.ks[0].at[slot, 0].set(jnp.nan)
        self.metrics.on_fault("nan_logits",
                              f"injected NaN into slot {slot} KV",
                              step=step_i)

    # ---------------------------------------------- watchdog / recovery
    def _publish_health(self) -> None:
        self.health.degraded = self.ladder.level > 0
        self.metrics.on_health_state(self.health.state,
                                     self.health.state_code,
                                     step=self._step_in_flight)

    def _on_step_fault(self, exc: Exception) -> int:
        """A step raised under the watchdog.  Attribution decides the
        response: a fault in the fused decode path feeds the ladder
        (composed decode is the always-available fallback); anything
        else consumes one retry from the backoff budget, and a spent
        budget quarantines.  State was already unwound by the step's own
        exception handling (admission requeues its batch, prefill faults
        abort their request), so 'retry' simply means the next step()
        runs normally after the backoff sleep."""
        step_i = self._step_in_flight
        phase = self._fault_phase or "step"
        if phase == "spec_verify" and self.spec_on \
                and not self.spec_bypass:
            # speculation is optional: its faults feed the ladder, which
            # disables it at threshold — decode is always the fallback
            self._subsystem_fault("spec_verify", exc)
        elif phase == "fused_decode" \
                and self.decode_path in ("fused", "tp_fused_block"):
            self._subsystem_fault("fused_decode", exc)
        else:
            self.metrics.on_fault(phase, repr(exc), step=step_i)
            backoff = self.health.record_step_fault(repr(exc))
            if backoff is None:
                self._quarantine(f"{phase} fault: {exc!r}")
            else:
                self.metrics.on_retry(self.health.consecutive_faults,
                                      backoff, step=step_i)
                if backoff > 0:
                    time.sleep(backoff)
        self._publish_health()
        return self.scheduler.active + self.scheduler.queue_depth

    def _subsystem_fault(self, subsystem: str, exc: Exception) -> None:
        """Count one fault against an OPTIONAL subsystem; at the ladder
        threshold the subsystem is disabled and the engine keeps serving
        without it (the fault site already contained the failure)."""
        self.metrics.on_fault(subsystem, repr(exc),
                              step=self._step_in_flight)
        if not self.ladder.disabled(subsystem) \
                and self.ladder.record_fault(subsystem):
            self._disable_subsystem(subsystem, repr(exc))

    def _disable_subsystem(self, subsystem: str, reason: str) -> None:
        """Apply one degradation-ladder rung (docs/serving.md ladder
        table).  Disabling is engine-lifetime — a subsystem that proved
        unreliable is not silently re-armed by a later rebuild."""
        if subsystem == "prefix_cache":
            self.prefix_bypass = True     # matches/inserts stop; live
            # pins release normally as their requests finish
        elif subsystem == "chunked_prefill":
            self.prefill_chunk = None     # whole-bucket prefill; plans
            # already computed keep their compiled chunk widths
        elif subsystem == "fused_decode":
            if self.tensor_parallel > 1:
                # the sharded-block rung degrades to the composed
                # compute-collective program when it is legal, the GSPMD
                # decode otherwise — the same order as the resolve chain
                from . import tp as _tp
                ok, tp_reason = _tp.tp_decode_supported(
                    self.model, self.tensor_parallel, self.num_slots) \
                    if self.collective_fusion \
                    else (False, "collective_fusion disabled")
                self.decode_path = "tp_fused" if ok else "unfused"
                self.tp_fusion_reason = None if ok else tp_reason
            else:
                self.decode_path = "unfused"
            self.decode_fallback_reason = f"degraded: {reason}"
            self._decode_fn = None        # re-trace composed on next use
            self._verify_fn = None        # verify is path-keyed too
        elif subsystem == "spec_verify":
            # back to one committed token per step; the draft tables
            # stay on their slots (pure host state, nothing reads them)
            self.spec_bypass = True
            self.spec_fallback_reason = f"degraded: {reason}"
            self.metrics.on_spec_disable(reason)
        else:
            raise ValueError(f"unknown subsystem {subsystem!r}")
        self.health.degraded = True
        self.metrics.on_degrade(subsystem, self.ladder.level, reason)

    def _quarantine(self, reason: str) -> None:
        """The step-retry budget is spent: fail the implicated in-flight
        requests terminally (their device state may hold donated
        garbage), rebuild the device plane, and leave queued work intact
        for re-serving.  ``enter_quarantine``/``leave_quarantine`` is a
        registered graftlint ``ResourcePair`` — the window closes on
        every path."""
        step_i = self._step_in_flight
        q = self.health.enter_quarantine(reason)
        try:
            self.metrics.on_quarantine("enter", reason, step=step_i)
            now = time.perf_counter()
            for st in list(self._prefills):
                self._abort_prefill(st, "failed", f"quarantine: {reason}")
            for slot in list(self._slots):
                req = self._slots[slot].req
                if not req.finished:
                    self._finalize(req, "failed",
                                   f"quarantine: {reason}", now=now)
                elif req.status is None:
                    # completed normally (eos/length) this very step but
                    # not yet evicted when the fault hit: stamp the
                    # NORMAL terminal accounting — quarantining an
                    # already-finished request must not fail it, nor
                    # leave it terminal with no status at all
                    self._finalize(req, "finished", req.finish_reason,
                                   now=now)
                self._release_slot(slot, now)
            self._build_device_plane()
            if self.health.circuit_open:
                self._open_circuit(reason)
        finally:
            seconds = self.health.leave_quarantine(q)
            self.metrics.on_quarantine("leave", reason, step=step_i,
                                       seconds=seconds)

    def _open_circuit(self, reason: str) -> None:
        """Too many quarantines inside the breaker window: stop
        flapping.  Everything queued fails terminally (nothing is ever
        silently dropped), submit() rejects with ``circuit_open``, and
        step() becomes a no-op — an operator decision (restart, new
        engine) is required past this point."""
        self.metrics.tracer.event("circuit_open",
                                  lane=self.metrics.engine_lane,
                                  reason=reason[:200],
                                  step=self._step_in_flight)
        while self.scheduler.waiting:
            req = self.scheduler.waiting.popleft()
            self._finalize(req, "failed", f"circuit open: {reason}")

    def _record_events(self, step_i: int, skips_before: int) -> None:
        """Turn this step's discrete happenings into event-log entries:
        trace-counter deltas = program compiles, scheduler skip-counter
        delta = head-of-line jumps (prefix-cache evictions report
        themselves through the ``on_event`` hook as they happen)."""
        tracer = self.metrics.tracer
        counts = dict(self.trace_counts)
        if self.block_pool is not None:
            counts.update({f"block_{k}": v
                           for k, v in self.block_pool.trace_counts.items()})
        for prog, n in counts.items():
            seen = self._compile_seen.get(prog, 0)
            if n > seen:
                self.metrics.on_compile(prog, n - seen)
                tracer.event("compile", lane=self.metrics.engine_lane,
                             program=prog,
                             count=n - seen, step=step_i)
        self._compile_seen = counts
        skips = self.scheduler.total_head_skips
        if skips > skips_before:
            tracer.event("head_of_line_skip",
                         lane=self.metrics.engine_lane,
                         count=skips - skips_before, step=step_i)

    def _emit(self, slot: int, tok: int, first_token: bool = False) -> None:
        st = self._slots[slot]
        req = st.req
        req.tokens.append(tok)
        if st.draft is not None:
            # the n-gram draft table learns every COMMITTED token, off
            # the hot path — harvest time, after the step's readback
            st.draft.observe(tok)
        self.progress_counter += 1              # token out = progress
        now = time.perf_counter()
        if first_token:
            req.first_token_time = now
            self.metrics.on_first_token(req.arrival_time, now=now)
            tracer = self.metrics.tracer
            if tracer.enabled:
                lane = self._lane(req)
                tracer.add_span("prefill", lane,
                                req.admit_time or req.arrival_time, now,
                                chunks=req.prefill_chunks,
                                hit_tokens=req.prefix_hit_tokens)
                tracer.event("first_token", lane=lane, t=now)
        elif req.last_token_time is not None:
            self.metrics.on_output_token(now - req.last_token_time)
        req.last_token_time = now
        if req.stream is not None:
            try:
                req.stream(req, tok)
            except Exception as e:
                # the CLIENT's sink broke, not the engine: fail exactly
                # this request (its token is already recorded) and keep
                # serving — a raising callback must never reach the
                # watchdog, where the step retry would silently desync
                # every OTHER slot from the already-advanced device state
                if not self.fault_tolerant:
                    raise
                self.metrics.on_fault("stream", repr(e),
                                      step=self._step_in_flight)
                self._finalize(req, "failed",
                               f"stream callback raised: {e!r}")
                return
        eos = req.eos_token_id
        if eos is not None and tok == eos:
            req.finished, req.finish_reason = True, "eos"
        elif len(req.tokens) >= req.max_new_tokens:
            req.finished, req.finish_reason = True, "length"

    def _harvest(self, slot: int, tok: int) -> int:
        st = self._slots.get(slot)
        if st is None:
            return 0  # reentrantly cancelled by a callback mid-harvest
        if st.req.finished:
            return 0  # finished at admit (eos/length on the first token)
        if tok == NONFINITE_SENTINEL:
            # the in-program finiteness probe tripped for THIS row: fail
            # exactly the implicated request (slot reclaimed by
            # _evict_finished this same step; the poisoned row is
            # overwritten wholesale by the next adopt)
            self.metrics.on_fault("nan_logits",
                                  f"non-finite logits in decode "
                                  f"(slot {slot})",
                                  step=self._step_in_flight)
            self._finalize(st.req, "failed",
                           "non-finite logits in decode")
            return 0
        st.pos += 1
        self._emit(slot, tok)
        return 1

    def _harvest_window(self, slot: int, toks) -> int:
        """Commit one slot's verify window — its accepted draft prefix
        plus the bonus token — through the SAME per-token path as
        sequential decode (:meth:`_harvest`), in order.  The loop
        breaks where the sequential engine would have stopped stepping:
        eos/length finishes, the non-finite sentinel, a reentrant
        cancel.  A truncated tail is simply discarded — the slot is
        evicted this same step, so its device state (which advanced by
        the full accepted length) is never read again."""
        emitted = 0
        for tok in toks:
            st = self._slots.get(slot)
            if st is None or st.req.finished:
                break
            got = self._harvest(slot, int(tok))
            if got == 0:
                break              # sentinel failed the request
            emitted += got
        return emitted

    # --------------------------------------------- terminal dispositions
    def _finalize(self, req: Request, status: str, reason: str,
                  now: Optional[float] = None) -> None:
        """Stamp one request's TERMINAL disposition — every submitted
        request passes through here exactly once (normal completions
        arrive from ``_evict_finished``/``_quarantine`` with
        ``status="finished"``), which is what the chaos suite's
        total-accounting invariant pins.  Does NOT touch slots/pins:
        the call site owns whatever unwinding its state demands."""
        if req.finished and req.status is not None:
            return                        # already terminal (idempotent)
        if now is None:
            now = time.perf_counter()
        req.finished = True
        req.status = status
        req.status_reason = reason
        req.finish_time = now
        self.progress_counter += 1        # a disposition is progress
        if status == "finished":
            self.metrics.on_finish()
        else:
            self.metrics.on_terminal(status, reason, req.request_id,
                                     now=now)
        if self.journal is not None:
            # exactly ONE terminal record per request: _finalize's
            # idempotence guard above is the single stamping path
            self._journal_hwm.pop(req.request_id, None)
            self.journal.append_terminal(req.request_id, status, reason,
                                         delivered=len(req.tokens))
        self._close_request_telemetry(req, now)

    def _close_request_telemetry(self, req: Request, now: float) -> None:
        tracer = self.metrics.tracer
        if not tracer.enabled:
            return
        lane = self._lane(req)
        if req.first_token_time is not None:
            tracer.add_span("decode", lane, req.first_token_time, now,
                            tokens=len(req.tokens))
        tracer.add_span("request", lane, req.arrival_time, now,
                        tokens=len(req.tokens),
                        finish_reason=req.finish_reason or req.status)

    def _release_slot(self, slot: int, now: float) -> Request:
        """Return one occupied slot's resources — scheduler entry, radix
        pin, pool slot, sampling row — in one place, so cancellation,
        deadline expiry, quarantine and normal eviction cannot drift
        apart in what they free."""
        req = self.scheduler.release(slot)
        st = self._slots.pop(slot)
        if st.match is not None:
            # unpin the request's radix path — its blocks become
            # LRU-evictable again (release is idempotent)
            self.prefix_cache.release(st.match)
        self.pool.free(slot)
        self._do_sample[slot] = False
        self._sampling_dev = None
        if not self._mask_host[slot].all():
            self._mask_host[slot] = True      # constrained row retires
            self._mask_dev = None
        tracer = self.metrics.tracer
        if tracer.enabled:
            tracer.event("slot_release", lane=self.metrics.engine_lane,
                         t=now, slot=slot, request=req.request_id,
                         reason=req.status_reason or req.finish_reason)
        return req

    def _abort_prefill(self, st: _Prefill, status: str,
                       reason: str) -> None:
        """Unwind one MID-PREFILL request (cancel / deadline / fault /
        quarantine): drop it from the prefill queue, return its slot and
        radix pin, stamp the terminal status.  The staging rows die with
        the last reference — they were never adopted into the pool."""
        if st in self._prefills:
            self._prefills.remove(st)
        self._slots.pop(st.slot, None)    # defensive: adopt may have run
        if st.match is not None:
            self.prefix_cache.release(st.match)
        self.scheduler.release(st.slot)
        self.pool.free(st.slot)
        self._do_sample[st.slot] = False
        self._sampling_dev = None
        if not self._mask_host[st.slot].all():
            self._mask_host[st.slot] = True
            self._mask_dev = None
        self._finalize(st.req, status, reason)

    def cancel(self, request_id: int, status: str = "cancelled",
               reason: str = "cancelled by client") -> bool:
        """Cleanly unwind one request in ANY state — queued,
        mid-(chunked-)prefill, or decoding — freeing its pool slot,
        staging rows and pinned radix path immediately.  Returns True
        when the request was found in flight (False: unknown id or
        already terminal — cancellation is idempotent)."""
        req = self.scheduler.remove_waiting(request_id)
        if req is not None:
            self._finalize(req, status, reason)
            return True
        for st in list(self._prefills):
            if st.req.request_id == request_id:
                self._abort_prefill(st, status, reason)
                return True
        for slot, sl in list(self._slots.items()):
            if sl.req.request_id == request_id and not sl.req.finished:
                now = time.perf_counter()
                self._finalize(sl.req, status, reason, now=now)
                self._release_slot(slot, now)
                return True
        return False

    def _expire_deadlines(self, now: float) -> None:
        """Host-side per-step deadline sweep (runs only once any
        submitted request has carried a deadline): queued requests whose
        budget is already blown never consume a slot; in-flight ones are
        unwound exactly like a cancel, with status
        ``deadline_exceeded``."""
        for req in self.scheduler.expired_waiting(now):
            self._finalize(req, "deadline_exceeded",
                           req.deadline_violation(now) or
                           "deadline exceeded", now=now)
        for st in list(self._prefills):
            v = st.req.deadline_violation(now)
            if v is not None:
                self._abort_prefill(st, "deadline_exceeded", v)
        for slot, sl in list(self._slots.items()):
            if sl.req.finished:
                continue
            v = sl.req.deadline_violation(now)
            if v is not None:
                self._finalize(sl.req, "deadline_exceeded", v, now=now)
                self._release_slot(slot, now)

    # ------------------------------------------------ submit-time gates
    def check_admission(self, req: Request) -> None:
        """Submit-time backpressure (docs/serving.md): bounded queue,
        SLO-aware rejection when the projected TTFT already exceeds the
        request's deadline, and fail-fast once the circuit is open.
        Raises :class:`RequestRejected` with a live-metrics retry hint;
        on acceptance, just records whether deadline sweeps are needed."""
        if self.fault_tolerant and self.health.circuit_open:
            self._reject(req, "circuit_open", None)
        if self.max_queue is not None \
                and self.scheduler.queue_depth >= self.max_queue:
            excess = self.scheduler.queue_depth - self.max_queue + 1
            self._reject(req, "queue_full",
                         self.metrics.retry_after_hint(excess))
        if req.ttft_deadline_s is not None:
            projected = self.metrics.projected_ttft_s(
                self.scheduler.queue_depth)
            if projected is not None \
                    and projected > req.ttft_deadline_s:
                self._reject(req, "slo_unattainable",
                             self.metrics.retry_after_hint())
        if req.deadline_s is not None or req.ttft_deadline_s is not None:
            self._deadlines_possible = True

    def _reject(self, req: Request, reason: str,
                retry_after_s: Optional[float]) -> None:
        req.finished = True
        req.status = "rejected"
        req.status_reason = reason
        req.finish_time = time.perf_counter()
        self.metrics.on_terminal("rejected", reason, req.request_id)
        raise RequestRejected(reason, retry_after_s)

    def _evict_finished(self) -> None:
        for slot in [s for s, st in self._slots.items() if st.req.finished]:
            now = time.perf_counter()
            req = self._release_slot(slot, now)
            if req.status is None:
                # normal completion (eos/length): abnormal statuses were
                # settled at their _finalize site, this loop reclaims
                self._finalize(req, "finished", req.finish_reason,
                               now=now)

    # ----------------------------------------------------- conveniences
    def stall_snapshot(self) -> Dict[str, object]:
        """Host-state diagnostic attached to
        :class:`~paddle_tpu.serving.errors.EngineStalledError` (and
        useful on its own for operator dumps)."""
        return {
            "queue_depth": self.scheduler.queue_depth,
            "active": self.scheduler.active,
            "mid_prefill": len(self._prefills),
            "free_slots": self.pool.free_slots,
            "free_blocks": None if self.block_pool is None
            else self.block_pool.free_blocks,
            "seq_pos": np.asarray(self.pool.seq_pos).tolist(),
            "health": self.health.state,
            "degraded_subsystems": list(self.ladder.disabled_subsystems),
            "progress_counter": self.progress_counter,
            "steps": self._step_index,
            "tensor_parallel": self.tensor_parallel,
            "speculation": self.spec_on and not self.spec_bypass,
        }

    def run_until_complete(self, max_steps: Optional[int] = None,
                           stall_steps: Optional[int] = 64) -> int:
        """Step until queue and slots drain; returns steps taken.

        ``stall_steps`` arms the no-progress detector: if that many
        CONSECUTIVE steps emit no token, admit no request, run no
        prefill chunk and settle no request while work is still queued,
        :class:`EngineStalledError` is raised with a diagnostic snapshot
        instead of spinning forever (None disables — the pre-robustness
        behavior)."""
        steps = 0
        stalled = 0
        last_progress = self.progress_counter
        while self.scheduler.has_work():
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"serving did not drain within {max_steps} steps")
            self.step()
            steps += 1
            if self.progress_counter != last_progress:
                last_progress = self.progress_counter
                stalled = 0
            else:
                stalled += 1
                if stall_steps is not None and stalled >= stall_steps \
                        and self.scheduler.has_work():
                    raise EngineStalledError(stalled,
                                             self.stall_snapshot())
        return steps
