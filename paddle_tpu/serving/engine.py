"""Continuous-batching engine core: the fixed-shape step loop.

Device plane (all jitted, all fixed-shape — graftlint's recompile-hazard
rule is the design constraint):

  * ``prefill``  — one program per CHUNK WIDTH: ``[1, width]`` tokens
    appended into a ``[1, max_seq]`` staging cache at a traced offset,
    returning the last-valid-token logits (a traced valid count selects
    the row, so padding never recompiles).  Width comes from the
    scheduler's chunk plan: without chunking, one pow2-bucketed chunk
    covers the whole uncached suffix (the classic shape); with
    ``prefill_chunk`` set, long suffixes run as fixed-width pieces
    interleaved with decode, so one 8k admission never stalls the
    in-flight streams for more than one chunk;
  * ``block copy`` — the radix prefix cache's two programs
    (kv_pool.BlockPool): gather matched prefix blocks into the staging
    cache at admission, scatter freshly computed blocks out of the slot
    at prefill completion.  A cache-hit request prefills ONLY its
    suffix — prefill FLOPs drop by the shared-prefix fraction and TTFT
    becomes O(suffix);
  * ``decode``   — ONE program, period: ``[num_slots, 1]`` tokens against
    the whole pool with per-slot positions (models/kv_cache.py), per-slot
    sampling params as traced row values, and per-slot PRNG keys.  Free
    and mid-prefill slots ride along as no-ops: their rows decode garbage
    that nothing reads, their writes land at positions a later adopt
    overwrites wholesale.

Host plane: ONE device->host readback per step phase — the decode
harvest reads the sampled token vector once, and a step that completes
prefills reads their batched first tokens once (all prefill dispatches
stay async until then).  Admission, radix-tree matching, eviction,
eos/length bookkeeping and metrics all run on host ints the engine
already holds.

Per-slot sampling reuses ``generation._filter_top_p`` directly (its
threshold broadcasts over rows) and generalises ``_filter_top_k`` to a
per-row traced k via rank masking (``_filter_top_k_rows`` — the static-k
form cannot vary k within one compiled step).  Each slot draws from its
OWN PRNG key with the same split discipline as ``generate``, so a
single-request engine run reproduces ``generate(seed=...)`` token for
token, sampling included.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import _filter_top_p
from .kv_pool import BlockPool, KVPool
from .metrics import ServingMetrics
from .prefix_cache import MatchResult, PrefixCache
from .scheduler import Request, Scheduler

__all__ = ["EngineCore", "sample_rows"]


def _filter_top_k_rows(logits, top_k):
    """Per-row top-k: keep each row's ``top_k[r]`` highest logits
    (``top_k[r] == 0`` keeps the whole row).  Rank masking — argsort of
    the descending argsort — matches ``generation._filter_top_k`` for
    distinct values and resolves ties by vocab order (the stable-sort
    winner), which is also what argmax picks for k=1."""
    order = jnp.argsort(-logits, axis=-1)
    rank = jnp.argsort(order, axis=-1)
    k = jnp.asarray(top_k, jnp.int32)[:, None]
    keep = jnp.where(k > 0, rank < k, True)
    return jnp.where(keep, logits, -jnp.inf)


def sample_rows(keys, logits, do_sample, temperature, top_k, top_p):
    """Per-row token selection over ``logits [rows, vocab]``.

    ``do_sample [rows] bool`` picks greedy argmax vs sampling per row;
    sampling rows apply ``temperature -> top_k -> top_p`` (the exact
    pipeline of ``generation.generate``) and draw from their OWN key row
    of ``keys [rows, key_dim]``, so one request's randomness never
    depends on its slot neighbours."""
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits / temp[:, None]
    filtered = _filter_top_k_rows(scaled, top_k)
    p = jnp.asarray(top_p, jnp.float32)[:, None]
    # rows with top_p == 1.0 skip the nucleus filter EXACTLY, matching
    # generate()'s static skip; filtered rows take the nucleus lane
    filtered = jnp.where(p >= 1.0, filtered, _filter_top_p(filtered, p))
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(jnp.asarray(do_sample, bool), sampled, greedy_tok)


class _Slot:
    """Host mirror of one pool slot's request progress."""

    __slots__ = ("req", "pos", "match")

    def __init__(self, req: Request, prompt_len: int,
                 match: Optional[MatchResult] = None):
        self.req = req
        self.pos = prompt_len       # cache length == next write offset
        self.match = match          # pinned radix-cache path, if any


class _Prefill:
    """A request mid-prefill: its slot is allocated, its context grows in
    a per-request staging cache (per-layer [1, max_seq] k/v rows seeded
    from the radix cache's matched blocks), and the scheduler's chunk
    plan drives one decode_step append per chunk."""

    __slots__ = ("req", "slot", "ks", "vs", "plan", "next_chunk", "match",
                 "last_logits")

    def __init__(self, req: Request, slot: int, ks, vs, plan,
                 match: Optional[MatchResult]):
        self.req = req
        self.slot = slot
        self.ks = ks                # staging caches, threaded per chunk
        self.vs = vs
        self.plan = plan            # [(offset, width, valid), ...]
        self.next_chunk = 0
        self.match = match
        self.last_logits = None     # final chunk's last-token logits

    @property
    def done(self) -> bool:
        return self.next_chunk >= len(self.plan)


class EngineCore:
    """Owns the pool, the radix prefix cache, the per-slot device state
    and the compiled step functions.  The public request/streaming
    surface lives in ``serving.api.ServingEngine``."""

    def __init__(self, model, num_slots: int = 8,
                 max_seq: Optional[int] = None,
                 min_bucket: int = 16,
                 max_prefills_per_step: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_prefill_tokens_per_step: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 block_len: int = 16,
                 prefix_blocks: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None,
                 fused_decode: bool = False):
        if prefill_chunk is not None and prefill_chunk < min_bucket:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} must be >= min_bucket "
                f"{min_bucket}")
        if max_prefill_tokens_per_step is not None \
                and max_prefill_tokens_per_step < 1:
            raise ValueError("max_prefill_tokens_per_step must be >= 1")
        self.model = model
        self.pool = KVPool.create(model, num_slots, max_seq)
        self.scheduler = Scheduler(num_slots, self.pool.max_seq,
                                   min_bucket=min_bucket,
                                   max_prefills_per_step=max_prefills_per_step)
        self.prefill_chunk = prefill_chunk
        self.max_prefill_tokens_per_step = max_prefill_tokens_per_step
        self.prefix_cache: Optional[PrefixCache] = None
        self.block_pool: Optional[BlockPool] = None
        if enable_prefix_cache:
            if block_len < 1:
                raise ValueError("block_len must be >= 1")
            # block_len must tile the slot row; shrink to the largest
            # pow2 divisor of max_seq when the requested size doesn't
            # (pow2 max_seqs — the common case — keep a pow2 request
            # verbatim).  Round DOWN to a pow2 first: halving a non-pow2
            # like 12 would otherwise walk 12->6->3->1 past the perfectly
            # good 8 and quietly build a per-token tree.
            block_len = 1 << (block_len.bit_length() - 1)
            while block_len > 1 and self.pool.max_seq % block_len:
                block_len //= 2
            # default pool size: as many blocks as the slot pool has rows
            # of context — a second slab the size of the first
            nb = prefix_blocks if prefix_blocks is not None else \
                num_slots * (self.pool.max_seq // block_len)
            self.block_pool = BlockPool.create(model, nb, block_len,
                                               self.pool.max_seq)
            self.prefix_cache = PrefixCache(self.block_pool)
        self.metrics = metrics or ServingMetrics()
        self.num_slots = num_slots
        self._slots: Dict[int, _Slot] = {}
        self._prefills: List[_Prefill] = []      # FCFS, mid-prefill
        # per-slot device row state (fixed [num_slots] shapes)
        self._last_tok = jnp.zeros((num_slots,), jnp.int32)
        key0 = jax.random.PRNGKey(0)
        self._keys = jnp.tile(key0[None], (num_slots,) + (1,) * key0.ndim)
        # per-slot sampling params: host numpy mirrors, re-uploaded to a
        # cached device copy only when admission/eviction dirties them
        # (values are traced row data — changing them never recompiles)
        self._do_sample = np.zeros((num_slots,), bool)
        self._temperature = np.ones((num_slots,), np.float32)
        self._top_k = np.zeros((num_slots,), np.int32)
        self._top_p = np.ones((num_slots,), np.float32)
        self._sampling_dev: Optional[Tuple] = None
        # compiled programs: ONE decode fn + ONE prefill fn whose jit
        # cache is keyed by the [1, width] chunk shape (one program per
        # chunk width / pow2 bucket, nothing per length); the trace
        # counters (plus BlockPool.trace_counts for the two block-copy
        # programs) are what the compile-count guard tests assert on
        self._decode_fn = None
        self._prefill_fn: Optional[Callable] = None
        self._staging_init_fn: Optional[Callable] = None
        self.trace_counts = {"prefill": 0, "decode": 0}
        # fused decode-block path (kernels/decode_block.py): opt-in flag,
        # resolved STATICALLY here — legality (shape/dtype/VMEM plan) and
        # routing never depend on runtime values, so the decode program
        # set stays {chunk} + buckets + ONE decode either way.  The
        # resolution lands in the decode_block obs event at compile time.
        self.fused_decode = fused_decode
        self.decode_path, self.decode_fallback_reason = \
            self._resolve_decode_path()
        # telemetry plumbing: the step index keys every phase span, the
        # compile baseline turns trace-counter ticks into discrete
        # events, and the prefix cache reports evictions through a hook
        self._step_index = 0
        # the step currently executing — lazily-built programs (e.g. the
        # decode fn on the first dispatch) tag their obs events with
        # this so they correlate with the surrounding serving.step span
        self._step_in_flight = 0
        self._compile_seen: Dict[str, int] = {}
        if self.prefix_cache is not None:
            # evictions land on THIS engine's timeline lane, not the
            # tracer's default lane 0 (another engine's, under sharing)
            self.prefix_cache.on_event = functools.partial(
                self.metrics.tracer.event, lane=self.metrics.engine_lane)

    def _lane(self, req: Request) -> int:
        """Tracer lane for one request's lifecycle spans (the engine's
        own step-phase timeline sits on ``metrics.engine_lane``; lanes
        are per-engine blocks, so engines sharing a tracer never
        collide)."""
        return self.metrics.request_lane(req.request_id)

    # ----------------------------------------------------------- prefill
    def _build_prefill_fn(self) -> Callable:
        model = self.model

        def prefill(ks, vs, ids, pos, valid):
            self.trace_counts["prefill"] += 1  # trace-time side effect
            caches = [(k, v, pos) for k, v in zip(ks, vs)]
            logits, caches = model.decode_step(ids, caches, pos)
            last = jnp.take_along_axis(
                logits, (valid - 1)[None, None, None], axis=1)[0, 0]
            return (last.astype(jnp.float32),
                    [c[0] for c in caches], [c[1] for c in caches])

        # donating the staging rows threads them chunk to chunk in place
        return jax.jit(prefill, donate_argnums=(0, 1))

    def _prefill_cost(self, req: Request) -> int:
        """Tokens of prefill work admitting ``req`` costs THIS step: the
        width of its first chunk, after the radix-cache match shrinks the
        suffix.  This is what the scheduler's head-of-line budget check
        sees — a long-prompt head with a long cached prefix is cheap."""
        matched = self.prefix_cache.match_length(req.prompt) \
            if self.prefix_cache is not None else 0
        plan = self.scheduler.chunk_plan(matched, req.prompt_len,
                                         self.prefill_chunk)
        return plan[0][1]

    def _begin_prefill(self, req: Request) -> None:
        """Claim a slot, match + pin the longest cached prefix, seed the
        staging cache from its block rows (one gather program), and queue
        the suffix's chunk plan.  No model FLOPs run here.  The slot and
        the pinned radix path are returned to their pools if anything
        between claim and placement raises — admission failure must not
        bleed capacity (resource-lifecycle rule)."""
        t_admit = time.perf_counter()
        slot = self.pool.alloc()
        match = None
        try:
            matched = 0
            t_match0 = t_match1 = t_admit
            if self.prefix_cache is not None:
                t_match0 = time.perf_counter()
                match = self.prefix_cache.match(req.prompt)
                matched = match.tokens
                t_match1 = time.perf_counter()
            t_gather0 = time.perf_counter()
            if matched:
                ks, vs = self.prefix_cache.load_staging(match)
            else:
                # ONE compiled zero-staging builder instead of 2*num_layers
                # eager jnp.zeros dispatches per miss admission
                if self._staging_init_fn is None:
                    model, max_seq = self.model, self.pool.max_seq

                    def fresh_staging():
                        caches = model.init_cache(1, max_seq)
                        return ([c[0] for c in caches],
                                [c[1] for c in caches])

                    self._staging_init_fn = jax.jit(fresh_staging)
                ks, vs = self._staging_init_fn()
            t_gather1 = time.perf_counter()
            plan = self.scheduler.chunk_plan(matched, req.prompt_len,
                                             self.prefill_chunk)
            self.scheduler.place(req, slot)
            # hit/telemetry accounting only after placement: a failed
            # admission is requeued and retried, and must not count its
            # hit (or record its lifecycle spans) twice
            if matched:
                req.prefix_hit_tokens = matched
                self.metrics.on_prefix_hit(matched)
            req.admit_time = t_admit
            self.metrics.on_queue_wait(t_admit - req.arrival_time)
            self.metrics.on_gather(t_gather1 - t_gather0)
            tracer = self.metrics.tracer
            if tracer.enabled:
                lane = self._lane(req)
                tracer.set_lane_name(lane, f"request {req.request_id}")
                tracer.add_span("queued", lane, req.arrival_time, t_admit,
                                prompt_len=req.prompt_len)
                if self.prefix_cache is not None:
                    tracer.add_span("prefix_match", lane, t_match0,
                                    t_match1, hit_tokens=matched)
                tracer.add_span("gather", lane, t_gather0, t_gather1,
                                hit=bool(matched))
            self._prefills.append(_Prefill(req, slot, ks, vs, plan, match))
        except BaseException:
            if match is not None:
                self.prefix_cache.release(match)
            self.pool.free(slot)
            raise

    def _run_chunk(self, st: _Prefill) -> None:
        """Dispatch one prefill chunk of ``st`` (async — no readback)."""
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill_fn()
        off, width, valid = st.plan[st.next_chunk]
        t0 = time.perf_counter()
        ids = np.zeros((1, width), np.int32)
        ids[0, :valid] = np.asarray(st.req.prompt[off:off + valid],
                                    np.int32)
        last_logits, st.ks, st.vs = self._prefill_fn(
            st.ks, st.vs, jnp.asarray(ids),
            jnp.asarray(off, jnp.int32), jnp.asarray(valid, jnp.int32))
        t1 = time.perf_counter()
        st.next_chunk += 1
        st.req.prefill_chunks += 1
        self.metrics.on_prefill_chunk(valid, seconds=t1 - t0)
        self.metrics.tracer.add_span(
            "prefill_chunk", self._lane(st.req), t0, t1,
            chunk=st.next_chunk - 1, width=width, tokens=valid)
        if st.done:
            st.last_logits = last_logits

    def _complete_prefill(self, st: _Prefill):
        """Final chunk done: sample the first token with the request's
        own key, adopt the staging row into the pool slot, and publish
        the freshly computed prompt blocks to the radix cache.  Returns
        ``(slot, first_token_array)`` — the caller batches the
        readbacks."""
        req, slot = st.req, st.slot
        key = jax.random.PRNGKey(req.sampling.seed)
        key, sub = jax.random.split(key)
        s = req.sampling
        first = sample_rows(
            sub[None], st.last_logits[None],
            jnp.asarray([s.do_sample]),
            jnp.asarray([s.temperature], jnp.float32),
            jnp.asarray([s.top_k], jnp.int32),
            jnp.asarray([s.top_p], jnp.float32))
        self.pool.adopt(slot, list(zip(st.ks, st.vs)), req.prompt_len)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, self.pool, slot)
        self._slots[slot] = _Slot(req, req.prompt_len, match=st.match)
        self._last_tok = self._last_tok.at[slot].set(first[0])
        self._keys = self._keys.at[slot].set(key)
        self._do_sample[slot] = s.do_sample
        self._temperature[slot] = s.temperature
        self._top_k[slot] = s.top_k
        self._top_p[slot] = s.top_p
        self._sampling_dev = None
        self.metrics.on_prefill(req.prompt_len - req.prefix_hit_tokens)
        return slot, first

    def _advance_prefills(self) -> int:
        """Run this step's prefill work.  Without chunking every pending
        prefill completes (the legacy admit-then-decode shape); with
        ``prefill_chunk`` set, exactly ONE chunk runs per step, so the
        per-step decode stall is bounded by one chunk regardless of how
        long the admitted prompt is.  Completed requests' first tokens
        come back in ONE batched readback.  Returns tokens emitted."""
        staged: List[Tuple[int, jax.Array]] = []
        if self.prefill_chunk is None:
            while self._prefills:
                st = self._prefills.pop(0)
                while not st.done:
                    self._run_chunk(st)
                staged.append(self._complete_prefill(st))
        elif self._prefills:
            st = self._prefills[0]
            self._run_chunk(st)
            if st.done:
                self._prefills.pop(0)
                staged.append(self._complete_prefill(st))
        if staged:
            toks = np.asarray(jnp.concatenate([f for _, f in staged]))
            for (slot, _), tok in zip(staged, toks):
                self._emit(slot, int(tok), first_token=True)
        return len(staged)

    # ------------------------------------------------------------ decode
    def _resolve_decode_path(self):
        """Statically resolve fused-vs-unfused for THIS engine's shapes:
        the flag opts in, ``decode_block_route`` applies the routing
        policy (flags + measured win region), and the model's
        ``fused_decode_supported`` checks shape/dtype/VMEM legality.
        Returns ``(path, fallback_reason)``; reason is None when fused
        engages (or the flag is simply off)."""
        if not self.fused_decode:
            return "unfused", None
        from ..kernels.decode_block import resolve_fused_decode
        ok, reason = resolve_fused_decode(self.model,
                                          batch=self.num_slots,
                                          kv_len=self.pool.max_seq)
        return ("fused", None) if ok else ("unfused", reason)

    def _build_decode_fn(self) -> Callable:
        model = self.model
        fused = self.decode_path == "fused"
        # the discrete obs event marks WHICH path this engine's single
        # decode program compiled with (and why, on fallback) — traces
        # distinguish fused from unfused steps without diffing configs
        self.metrics.on_decode_block(
            active=fused,
            reason=None if not self.fused_decode
            else self.decode_fallback_reason,
            step=self._step_in_flight)

        def decode(ks, vs, seq_pos, last_tok, keys, do_sample,
                   temperature, top_k, top_p):
            self.trace_counts["decode"] += 1  # trace-time side effect
            caches = [(k, v, seq_pos) for k, v in zip(ks, vs)]
            step_fn = model.fused_decode_step if fused else \
                model.decode_step
            logits, caches = step_fn(last_tok[:, None], caches, seq_pos)
            split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
            nxt = sample_rows(split[:, 1], logits[:, 0], do_sample,
                              temperature, top_k, top_p)
            new_ks = [c[0] for c in caches]
            new_vs = [c[1] for c in caches]
            return (new_ks, new_vs, caches[0][2], nxt.astype(jnp.int32),
                    split[:, 0])

        # donating the KV slabs aliases them in place — pool memory stays
        # a single allocation across the whole serving run
        return jax.jit(decode, donate_argnums=(0, 1))

    def _decode_dispatch(self) -> jax.Array:
        """ONE fixed-shape decode step over every slot; returns the
        sampled token vector STILL ON DEVICE — the caller performs the
        step's single host readback (step() times dispatch and readback
        as separate timeline phases)."""
        if self._decode_fn is None:
            self._decode_fn = self._build_decode_fn()
        if self._sampling_dev is None:
            self._sampling_dev = (jnp.asarray(self._do_sample),
                                  jnp.asarray(self._temperature),
                                  jnp.asarray(self._top_k),
                                  jnp.asarray(self._top_p))
        ks, vs, pos, nxt, self._keys = self._decode_fn(
            self.pool.ks, self.pool.vs, self.pool.seq_pos,
            self._last_tok, self._keys, *self._sampling_dev)
        self.pool.ks, self.pool.vs, self.pool.seq_pos = ks, vs, pos
        self._last_tok = nxt
        return nxt

    # -------------------------------------------------------- step loop
    def step(self) -> int:
        """One engine iteration: admit (radix match + staging), advance
        prefill chunks, one decode step over all active slots, harvest
        tokens / evict finished.  Returns the number of requests still
        in flight (prefilling + running + queued).

        Telemetry rides the loop off the hot path: the step's phase
        breakdown (admission / prefill / decode dispatch / readback)
        lands as ``step.*`` spans on the engine lane + per-phase
        histograms, and trace-counter deltas / head-of-line skips /
        evictions become discrete events.  The per-slot token readback
        stays the step's ONLY device sync."""
        t0 = time.perf_counter()
        tracer = self.metrics.tracer
        step_i = self._step_index
        self._step_index += 1
        self._step_in_flight = step_i
        skips_before = self.scheduler.total_head_skips
        ann = None
        if self.metrics.record_events:
            from ..profiler import RecordEvent
            ann = RecordEvent("serving.step")
            ann.begin()
        sp = tracer.begin_span("serving.step",
                               lane=self.metrics.engine_lane,
                               step=step_i)
        try:
            admitted = self.scheduler.admit(
                self.pool.free_slots,
                token_budget=self.max_prefill_tokens_per_step,
                cost=self._prefill_cost)
            for i, (req, _) in enumerate(admitted):
                try:
                    self._begin_prefill(req)
                except BaseException:
                    # admission failure must not LOSE requests: the
                    # failing one and the rest of the popped batch go
                    # back to the queue head (their slots/pins were
                    # already returned)
                    self.scheduler.requeue_front(
                        [r for r, _ in admitted[i:]])
                    raise
            t_admit = time.perf_counter()
            new_tokens = self._advance_prefills()
            t_prefill = time.perf_counter()
            phases = [("admission", t0, t_admit),
                      ("prefill", t_admit, t_prefill)]
            if self._slots:
                nxt = self._decode_dispatch()
                t_decode = time.perf_counter()
                toks = np.asarray(nxt)     # THE per-step device readback
                t_readback = time.perf_counter()
                for slot in sorted(self._slots):
                    new_tokens += self._harvest(slot, int(toks[slot]))
                # decode phases exist only on steps that decoded — a
                # prefill-only step must not feed 0.0 into their
                # histograms and fake slices into the timeline
                phases += [("decode_dispatch", t_prefill, t_decode),
                           ("readback", t_decode, t_readback)]
                if self.decode_path == "fused":
                    # fused-path dispatch cost, separable from unfused
                    # runs in the same registry (glossary:
                    # kernel.decode_block_s, docs/observability.md)
                    self.metrics.on_decode_block_step(t_decode - t_prefill)
            self._evict_finished()
        finally:
            # a raised step must still close the span and the trace
            # annotation, or every later event nests inside a phantom
            # serving.step (resource-lifecycle rule: begin_span/end_span)
            tracer.end_span(sp)
            if ann is not None:
                ann.end()
        self._record_events(step_i, skips_before)
        self.metrics.record_step(
            active_slots=len(self._slots), num_slots=self.num_slots,
            queue_depth=self.scheduler.queue_depth,
            new_tokens=new_tokens,
            step_seconds=time.perf_counter() - t0,
            step_index=step_i,
            phases=phases)
        return self.scheduler.active + self.scheduler.queue_depth

    def _record_events(self, step_i: int, skips_before: int) -> None:
        """Turn this step's discrete happenings into event-log entries:
        trace-counter deltas = program compiles, scheduler skip-counter
        delta = head-of-line jumps (prefix-cache evictions report
        themselves through the ``on_event`` hook as they happen)."""
        tracer = self.metrics.tracer
        counts = dict(self.trace_counts)
        if self.block_pool is not None:
            counts.update({f"block_{k}": v
                           for k, v in self.block_pool.trace_counts.items()})
        for prog, n in counts.items():
            seen = self._compile_seen.get(prog, 0)
            if n > seen:
                self.metrics.on_compile(prog, n - seen)
                tracer.event("compile", lane=self.metrics.engine_lane,
                             program=prog,
                             count=n - seen, step=step_i)
        self._compile_seen = counts
        skips = self.scheduler.total_head_skips
        if skips > skips_before:
            tracer.event("head_of_line_skip",
                         lane=self.metrics.engine_lane,
                         count=skips - skips_before, step=step_i)

    def _emit(self, slot: int, tok: int, first_token: bool = False) -> None:
        req = self._slots[slot].req
        req.tokens.append(tok)
        now = time.perf_counter()
        if first_token:
            req.first_token_time = now
            self.metrics.on_first_token(req.arrival_time, now=now)
            tracer = self.metrics.tracer
            if tracer.enabled:
                lane = self._lane(req)
                tracer.add_span("prefill", lane,
                                req.admit_time or req.arrival_time, now,
                                chunks=req.prefill_chunks,
                                hit_tokens=req.prefix_hit_tokens)
                tracer.event("first_token", lane=lane, t=now)
        elif req.last_token_time is not None:
            self.metrics.on_output_token(now - req.last_token_time)
        req.last_token_time = now
        if req.stream is not None:
            req.stream(req, tok)
        eos = req.eos_token_id
        if eos is not None and tok == eos:
            req.finished, req.finish_reason = True, "eos"
        elif len(req.tokens) >= req.max_new_tokens:
            req.finished, req.finish_reason = True, "length"

    def _harvest(self, slot: int, tok: int) -> int:
        st = self._slots[slot]
        if st.req.finished:
            return 0  # finished at admit (eos/length on the first token)
        st.pos += 1
        self._emit(slot, tok)
        return 1

    def _evict_finished(self) -> None:
        for slot in [s for s, st in self._slots.items() if st.req.finished]:
            req = self.scheduler.release(slot)
            now = time.perf_counter()
            req.finish_time = now
            if self._slots[slot].match is not None:
                # unpin the request's radix path — its blocks become
                # LRU-evictable again
                self.prefix_cache.release(self._slots[slot].match)
            self.pool.free(slot)
            del self._slots[slot]
            self._do_sample[slot] = False
            self._sampling_dev = None
            self.metrics.on_finish()
            tracer = self.metrics.tracer
            if tracer.enabled:
                lane = self._lane(req)
                first = req.first_token_time or now
                tracer.add_span("decode", lane, first, now,
                                tokens=len(req.tokens))
                tracer.add_span("request", lane, req.arrival_time, now,
                                tokens=len(req.tokens),
                                finish_reason=req.finish_reason)
                tracer.event("slot_release",
                             lane=self.metrics.engine_lane, t=now,
                             slot=slot, request=req.request_id,
                             reason=req.finish_reason)

    # ----------------------------------------------------- conveniences
    def run_until_complete(self, max_steps: Optional[int] = None) -> int:
        """Step until queue and slots drain; returns steps taken."""
        steps = 0
        while self.scheduler.has_work():
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"serving did not drain within {max_steps} steps")
            self.step()
            steps += 1
        return steps
