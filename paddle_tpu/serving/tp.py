"""Tensor-parallel serving: the mesh, the sharded layouts, the fused
compute-collective decode program.

The serving engine (serving/engine.py) becomes multi-chip by sharding
its WHOLE device plane over a 1-D mesh whose axis is the models' ``mp``
(model-parallel) axis:

  * **KV slot slabs** (kv_pool.KVPool) and the radix **block slab**
    (kv_pool.BlockPool) partition on the kv-head axis — every device
    holds every slot, but only its head group;
  * **weights** partition Megatron-style: QKV / MLP-up column-wise,
    out-proj / MLP-down row-wise, embedding/head on the vocab axis (the
    specs the models already carry for training, reused verbatim for
    GPT; llama's serving layout mirrors ``llama_shard_fn``);
  * the engine's compiled surface ({chunk} + pow2 prefill buckets + ONE
    decode + gather + scatter + sampling) keeps its exact program-set
    size: prefill/gather/scatter/sampling run as GSPMD-partitioned
    programs over the same mesh (sharded operands in, XLA inserts the
    collectives), and the decode step — the latency-critical program —
    runs as ONE explicit shard_map whose TP collectives are fused into
    their adjacent dots (kernels/collective_matmul.py): the entry
    all-gather rides the QKV / MLP-up matmul, the exit reduce-scatter
    rides the out-proj / MLP-down matmul, and the residual stream stays
    slot-sharded between them so norms run local.  With
    ``pallas_block=True`` (the engine's ``tp_fused_block`` path, ISSUE
    12) the same program's layer bodies run the SHARDED Pallas decode
    block instead (kernels/decode_block_tp.py: the rings lowered into
    the Pallas grid, KV append in-kernel on the local slab shard).  See
    docs/serving.md "Tensor-parallel serving".

Per-device decode dataflow (one layer; B slots, tp devices)::

    x [B/tp, D] --norm--> allgather_matmul --> qkv [B, (H+2KH)/tp * dh]
      --rotary/append (local slab shard)--> decode attention (local
      heads) --> matmul_reduce_scatter(out-proj) --> [B/tp, D] +residual
      --norm--> allgather_matmul(MLP up) --> act -->
      matmul_reduce_scatter(MLP down) --> [B/tp, D] +residual

Logits leave the shard_map vocab-sharded (the final allgather_matmul
contracts hidden against the local vocab columns); sampling runs on the
sharded logits under GSPMD inside the same jitted decode program, so the
argmax/top-k reductions over vocab are partitioned too.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["TP_AXIS", "build_serving_mesh", "serving_param_specs",
           "shard_model_params", "sharded_zeros", "tp_decode_supported",
           "build_tp_decode_program", "build_tp_verify_program"]

# graftprog entry-point marker (see tools/analysis/compile_surface.py):
# the TP decode/verify program factories root their shard_map compile
# units on the static manifest.  Read by the AST analysis only; zero
# runtime effect.
__compile_surface_roots__ = ("build_tp_decode_program",
                             "build_tp_verify_program")

# the serving TP axis IS the models' model-parallel axis: the
# Column/RowParallelLinear layers annotate their weights over "mp"
# (distributed/meta_parallel/mp_layers.py), so naming the serving mesh
# the same way lets training specs and activation constraints bind
# unchanged under the serving mesh
TP_AXIS = "mp"

# slot slabs [num_slots, max_seq, kv_heads, head_dim] and block slabs
# [num_blocks, block_len, kv_heads, head_dim] both partition on the
# kv-head axis — axis 2 in either layout
KV_SLAB_SPEC = P(None, None, "mp", None)


def build_serving_mesh(tp: int, devices=None) -> Mesh:
    """A 1-D tensor-parallel mesh over ``tp`` devices (the first ``tp``
    of ``jax.devices()`` by default — on the CPU tier this is the
    XLA_FLAGS virtual-device mesh the MULTICHIP dryruns use)."""
    if tp < 1:
        raise ValueError(f"tensor_parallel must be >= 1, got {tp}")
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) < tp:
        raise ValueError(
            f"tensor_parallel={tp} needs {tp} devices but only "
            f"{len(devices)} are visible — on CPU set "
            f"--xla_force_host_platform_device_count (XLA_FLAGS)")
    return Mesh(np.array(devices[:tp]), ("mp",))


# --------------------------------------------------------------- layouts
def serving_param_specs(model) -> Dict[str, P]:
    """Dotted-name -> PartitionSpec for the engine's GSPMD programs
    (prefill chunks, staging init, block gather/scatter, sampling).

    Models that already carry TP training specs (GPT's parallel layers
    annotate over ``mp`` via set_param_spec) reuse them verbatim; plain
    models (llama) get the Megatron serving layout by leaf name —
    q/k/v/gate/up column-parallel, o/down row-parallel, embedding and
    lm_head vocab-parallel (embedding ROW-sharded so the fused decode
    bundle and the GSPMD table are one layout)."""
    from ..distributed.sharding_utils import get_param_specs
    specs = get_param_specs(model)
    if any(tuple(s) for s in specs.values()):
        return specs
    col = {"q_proj", "k_proj", "v_proj", "gate_proj", "up_proj", "lm_head"}
    row = {"o_proj", "down_proj"}
    out = {}
    for name in specs:
        parts = name.split(".")
        parent = parts[-2] if len(parts) >= 2 else ""
        if parent in col:
            out[name] = P(None, "mp")
        elif parent in row:
            out[name] = P("mp", None)
        elif parent == "embed_tokens":
            out[name] = P("mp", None)
        else:
            out[name] = P()
    return out


def _spec_fits(shape, spec: P, mesh: Mesh) -> bool:
    for dim, ax in zip(shape, tuple(spec)):
        if ax is None:
            continue
        axes = (ax,) if isinstance(ax, str) else tuple(ax)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        if dim % size:
            return False
    return True


def shard_model_params(model, mesh: Mesh) -> None:
    """Lay the model's parameters out over the serving mesh IN PLACE
    (each param with its serving spec; non-divisible dims fall back to
    replicated).  The engine's jitted programs close over these arrays,
    so every program compiles against the sharded layout.  Layout goes
    through ``sharding_utils.put_global`` — the multi-controller-safe
    ingest — so a multi-host pod slice lays out the same way as a
    single-host mesh."""
    from ..distributed.sharding_utils import put_global
    specs = serving_param_specs(model)
    for lname, sub in model.named_sublayers(include_self=True):
        for pname, p in list(sub._parameters.items()):
            if p is None:
                continue
            key = f"{lname}.{pname}" if lname else pname
            spec = specs.get(key, P())
            if not _spec_fits(p.shape, spec, mesh):
                spec = P()
            sub._parameters[pname] = put_global(
                p, NamedSharding(mesh, spec))


# one compiled zero-filler per (mesh, shape, dtype): pool construction
# and every quarantine rebuild reuse the same program, so slab creation
# is not a recompile treadmill
_ZEROS_CACHE: Dict[tuple, object] = {}


def sharded_zeros(mesh: Mesh, shape, dtype):
    """A builder for kv-head-sharded slabs ([rows, len, kv_heads,
    head_dim]) that are BORN sharded: a jitted zero-fill with
    ``out_shardings`` places each device's shard directly, so the full
    slab never materializes on one device — at pod scale it may not
    fit one, which is the point of sharding it.

    (An eager ``make_array_from_callback`` variant was tried and
    reverted: on the jaxlib-0.4 pin its per-shard host buffers
    nondeterministically crash the cyclic-GC pass conftest already
    documents — the compiled form has never shown it.)"""
    shape, dt = tuple(shape), jnp.dtype(dtype)
    key = (mesh, shape, dt.name)
    fn = _ZEROS_CACHE.get(key)
    if fn is None:
        ns = NamedSharding(mesh, KV_SLAB_SPEC)
        fn = jax.jit(functools.partial(jnp.zeros, shape, dt),
                     out_shardings=ns)
        _ZEROS_CACHE[key] = fn
    return fn


def replicated(x, mesh: Mesh):
    from ..distributed.sharding_utils import put_global
    return put_global(x, NamedSharding(mesh, P()))


# ------------------------------------------------- fused decode program
def tp_decode_supported(model, tp: int,
                        num_slots: int) -> Tuple[bool, Optional[str]]:
    """Static legality of the fused compute-collective decode program
    for ``model`` at this engine shape.  Returns ``(ok, reason)``."""
    if tp == 1:
        return False, "tensor_parallel is 1 (single chip needs no " \
                      "collectives)"
    if not hasattr(model, "tp_decode_weights") \
            or not hasattr(model, "tp_decode_supported"):
        return False, "model has no tp_decode_weights"
    if num_slots % tp:
        return False, (f"num_slots {num_slots} not divisible by "
                       f"tensor_parallel {tp} (the residual stream "
                       f"slot-shards between the fused collectives)")
    return model.tp_decode_supported(tp)


# per-leaf PartitionSpecs of the fused-decode weight bundle (the models'
# tp_decode_weights arranges the globals so an equal contiguous split
# over the mesh axis IS the per-device block)
_BUNDLE_SPECS = {
    "wte": P("mp", None),       # vocab-sharded rows (masked lookup+psum)
    "wpe": P(),                 # learned positions: tiny, replicated
    "head": P(None, "mp"),      # vocab column shard (None when tied)
    "nfw": P(), "nfb": P(),
    "n1w": P(), "n1b": P(), "n2w": P(), "n2b": P(),
    "wqkv": P(None, "mp"), "bqkv": P("mp"),
    "wo": P("mp", None), "bo": P(),
    "wup": P(None, "mp"), "bup": P("mp"),
    "wdown": P("mp", None), "bdown": P(),
}


def _bundle_specs(weights):
    def spec_of(d):
        return {k: (None if v is None
                    else [spec_of(b) for b in v] if k == "blocks"
                    else _BUNDLE_SPECS[k])
                for k, v in d.items()}
    return spec_of(weights)


def _norm(x, w, b, kind: str, eps: float):
    from ..nn import functional as F
    if kind == "rms":
        return F.rms_norm(x, w, None, eps)
    return F.layer_norm(x, (x.shape[-1],), w, b, eps)


def _tp_layer(x_s, pk, pv, seq_pos, blk, arch, rope, axis, tp, overlap,
              s: int = 1):
    """One transformer layer of the per-device decode body: entry
    all-gather fused into the QKV / MLP-up dots, exit reduce-scatter
    fused into the out-proj / MLP-down dots, attention local to this
    device's head group against its slab shard.

    ``s`` is the per-slot token width — 1 for the decode program, the
    ``spec_k+1`` verify window for the speculative verify program.  Rows
    stay flat ``[slots*s, features]`` (slot-major) through the fused
    collective dots and fold back to ``[slots, s, ...]`` only around
    attention, whose ragged visibility comes from ``cache_lens(pos, s)``
    — query t of a slot's window sees keys up to ``pos + t``."""
    from ..kernels.collective_matmul import (allgather_matmul,
                                             matmul_reduce_scatter)
    from ..kernels.decode_attention import decode_attention_auto
    from ..models.kv_cache import append_kv, cache_lens
    from ..nn import functional as F
    dh = arch["head_dim"]
    h_l = arch["heads"] // tp
    kh_l = arch["kv_heads"] // tp
    # ---- attention: norm (local rows) -> fused all-gather/QKV dot
    h1 = _norm(x_s, blk["n1w"], blk["n1b"], arch["norm"], arch["eps"])
    qkv = allgather_matmul(h1, blk["wqkv"], axis, tp, overlap=overlap)
    if blk["bqkv"] is not None:
        qkv = qkv + blk["bqkv"]
    rows = qkv.shape[0]
    b = rows // s
    q = qkv[:, :h_l * dh].reshape(b, s, h_l, dh)
    k = qkv[:, h_l * dh:(h_l + kh_l) * dh].reshape(b, s, kh_l, dh)
    v = qkv[:, (h_l + kh_l) * dh:].reshape(b, s, kh_l, dh)
    if rope is not None:
        from ..models.llama import apply_rotary_pos_emb
        cos, sin = rope
        q = apply_rotary_pos_emb(q, cos, sin)
        k = apply_rotary_pos_emb(k, cos, sin)
    k_buf, v_buf = append_kv(pk, pv, k, v, seq_pos)
    lens = cache_lens(seq_pos, s, b)
    rep = h_l // kh_l
    kk = jnp.repeat(k_buf, rep, axis=2) if rep > 1 else k_buf
    vv = jnp.repeat(v_buf, rep, axis=2) if rep > 1 else v_buf
    attn = decode_attention_auto(q, kk, vv, lens)       # [B, s, h_l, dh]
    attn = attn.reshape(rows, h_l * dh)
    # ---- exit: out-proj dot with the reduce-scatter riding it
    o = matmul_reduce_scatter(attn, blk["wo"], axis, tp, overlap=overlap)
    if blk["bo"] is not None:
        o = o + blk["bo"]
    x_s = x_s + o
    # ---- MLP: same entry/exit fusion pattern
    h2 = _norm(x_s, blk["n2w"], blk["n2b"], arch["norm"], arch["eps"])
    up = allgather_matmul(h2, blk["wup"], axis, tp, overlap=overlap)
    if blk["bup"] is not None:
        up = up + blk["bup"]
    if arch["act"] == "swiglu":
        f_l = up.shape[1] // 2
        act = F.silu(up[:, :f_l]) * up[:, f_l:]
    else:
        act = F.gelu(up, approximate=True)
    d = matmul_reduce_scatter(act, blk["wdown"], axis, tp, overlap=overlap)
    if blk["bdown"] is not None:
        d = d + blk["bdown"]
    return x_s + d, k_buf, v_buf


def _tp_decode_body(weights, ks, vs, seq_pos, last_tok, *, arch, tp,
                    axis, overlap, pallas_plan=None):
    """Per-device body of the ONE fused decode program: embed (masked
    vocab-shard lookup + psum) -> slot-shard the residual stream ->
    layers (fused collectives) -> final norm -> logits against the local
    vocab columns (left vocab-sharded for the GSPMD sampling tail).

    With ``pallas_plan`` the layer bodies run as the SHARDED Pallas
    decode-block kernels (kernels/decode_block_tp.py — the entry/exit
    rings lowered into the Pallas grid, attention + in-kernel append on
    the local slab shard); the embed / final-norm / logits legs are
    shared code either way, so the two paths cannot drift outside the
    layer seam."""
    from ..kernels.collective_matmul import allgather_matmul
    idx = jax.lax.axis_index(axis)
    b = last_tok.shape[0]
    b_l = b // tp
    wte_l = weights["wte"]                       # [V/tp, D] local rows
    v_l = wte_l.shape[0]
    loc = last_tok.astype(jnp.int32) - idx * v_l
    ok = (loc >= 0) & (loc < v_l)
    emb = jnp.take(wte_l, jnp.clip(loc, 0, v_l - 1), axis=0)
    emb = jnp.where(ok[:, None], emb, jnp.zeros((), emb.dtype))
    x = jax.lax.psum(emb, axis)                  # [B, D] replicated
    if weights["wpe"] is not None:
        x = x + jnp.take(weights["wpe"], seq_pos, axis=0)
    rope, rope_full = None, None
    if arch["rope"]:
        from ..models.llama import _rope_tables
        if pallas_plan is not None:
            # full-width tables (halves duplicated) at each slot's
            # position — the kernel applies rotary in matrix form,
            # exactly like the models' tp=1 fused_decode_step
            cos, sin = _rope_tables(seq_pos, arch["head_dim"],
                                    arch["rope_theta"], jnp.float32)
            rope_full = (jnp.concatenate([cos, cos], axis=-1),
                         jnp.concatenate([sin, sin], axis=-1))
        else:
            cos, sin = _rope_tables(seq_pos[:, None], arch["head_dim"],
                                    arch["rope_theta"], x.dtype)
            rope = (cos, sin)
    # slot-shard the residual stream: this device's row chunk
    x_s = jax.lax.dynamic_slice_in_dim(x, idx * b_l, b_l, axis=0)
    new_ks, new_vs = [], []
    for blk, pk, pv in zip(weights["blocks"], ks, vs):
        if pallas_plan is not None:
            from ..kernels.decode_block_tp import tp_fused_block_layer
            x_s, kb, vb = tp_fused_block_layer(
                x_s, pk, pv, seq_pos, blk, arch, rope_full, axis, tp,
                pallas_plan)
        else:
            x_s, kb, vb = _tp_layer(x_s, pk, pv, seq_pos, blk, arch,
                                    rope, axis, tp, overlap)
        new_ks.append(kb)
        new_vs.append(vb)
    xf = _norm(x_s, weights["nfw"], weights["nfb"], arch["norm"],
               arch["eps"])
    head_l = weights["head"] if weights["head"] is not None else wte_l.T
    logits = allgather_matmul(xf, head_l, axis, tp, overlap=overlap)
    return logits[:, None, :], new_ks, new_vs, seq_pos + 1


def build_tp_decode_program(model, mesh: Mesh, tp: int, *,
                            overlap: bool = True,
                            pallas_block: bool = False,
                            batch: Optional[int] = None,
                            max_seq: Optional[int] = None):
    """Build the engine's fused compute-collective decode program:
    ``fn(ks, vs, seq_pos, last_tok) -> (logits, new_ks, new_vs,
    new_pos)`` with ``logits [num_slots, 1, vocab]`` vocab-sharded over
    the mesh.  NOT jitted — the engine wraps it together with its
    sampling tail in the single compiled decode step, so the program-set
    pin (ONE decode) is unchanged.

    ``pallas_block=True`` builds the ``tp_fused_block`` variant: the
    layer bodies run the sharded Pallas decode-block kernels
    (kernels/decode_block_tp.py) with the entry/exit collectives riding
    the tile dots and the KV append landing in-kernel on the local slab
    shard; ``batch``/``max_seq`` (the engine's num_slots / pool
    max_seq) size the per-shard VMEM plan, which raises if illegal —
    callers are contracted to gate on
    ``decode_block.resolve_fused_decode(tp=...)`` first.

    The weight bundle is laid out here once (device_put per
    ``_BUNDLE_SPECS``); the returned closure captures it, exactly like
    the composed path captures the model's own parameters."""
    from ..distributed._jax_compat import shard_map
    from ..distributed.sharding_utils import put_global
    arch, weights = model.tp_decode_weights(tp)
    pallas_plan = None
    if pallas_block:
        from ..kernels.decode_block import plan_decode_block
        gated = arch["act"] == "swiglu"
        blk0 = weights["blocks"][0]
        ffn = blk0["wup"].shape[1] // (2 if gated else 1)
        pallas_plan, why = plan_decode_block(
            max_seq=max_seq, hidden=arch["hidden"], heads=arch["heads"],
            kv_heads=arch["kv_heads"], head_dim=arch["head_dim"],
            ffn=ffn, batch=batch,
            itemsize=jnp.dtype(blk0["wqkv"].dtype).itemsize,
            gated=gated, tp=tp)
        if pallas_plan is None:
            raise ValueError(
                f"build_tp_decode_program(pallas_block=True): no VMEM "
                f"tiling fits ({why}) — gate on resolve_fused_decode "
                f"before requesting the sharded Pallas block")
    specs = _bundle_specs(weights)
    weights = jax.tree.map(
        lambda w, s: None if w is None
        else put_global(w, NamedSharding(mesh, s)),
        weights, specs, is_leaf=lambda x: x is None)
    num_layers = len(weights["blocks"])
    body = functools.partial(_tp_decode_body, arch=arch, tp=tp,
                             axis=TP_AXIS, overlap=overlap,
                             pallas_plan=pallas_plan)
    slab = [KV_SLAB_SPEC] * num_layers

    def program(ks, vs, seq_pos, last_tok):
        return shard_map(
            body, mesh=mesh,
            in_specs=(specs, slab, slab, P(), P()),
            out_specs=(P(None, None, "mp"), slab, slab, P()),
            check_vma=False)(weights, ks, vs, seq_pos, last_tok)

    return program


def _tp_verify_body(weights, ks, vs, seq_pos, ids, *, arch, tp, axis,
                    overlap, width):
    """Per-device body of the ONE fused verify program — the decode
    body at token width ``width`` (= spec_k+1): the ``[B, width]``
    draft windows flatten slot-major to ``[B*width]`` rows so the same
    fused compute-collective dots carry them, each slot's window sits
    at its OWN ``seq_pos`` (embedding offsets, rope, and the ragged
    ``cache_lens`` attention all take per-row position vectors), and
    the layer seam is the SAME ``_tp_layer`` the decode program
    compiles — the two paths cannot drift."""
    from ..kernels.collective_matmul import allgather_matmul
    idx = jax.lax.axis_index(axis)
    b, s = ids.shape
    b_l = b // tp
    flat = ids.reshape(b * s).astype(jnp.int32)
    wte_l = weights["wte"]                       # [V/tp, D] local rows
    v_l = wte_l.shape[0]
    loc = flat - idx * v_l
    ok = (loc >= 0) & (loc < v_l)
    emb = jnp.take(wte_l, jnp.clip(loc, 0, v_l - 1), axis=0)
    emb = jnp.where(ok[:, None], emb, jnp.zeros((), emb.dtype))
    x = jax.lax.psum(emb, axis)                  # [B*s, D] replicated
    pos2d = seq_pos[:, None] + jnp.arange(s)     # [B, s] per-row offsets
    if weights["wpe"] is not None:
        x = x + jnp.take(weights["wpe"], pos2d.reshape(b * s), axis=0)
    rope = None
    if arch["rope"]:
        from ..models.llama import _rope_tables
        cos, sin = _rope_tables(pos2d, arch["head_dim"],
                                arch["rope_theta"], x.dtype)
        rope = (cos, sin)
    # slot-shard the residual stream: this device's slot-major row chunk
    x_s = jax.lax.dynamic_slice_in_dim(x, idx * b_l * s, b_l * s, axis=0)
    new_ks, new_vs = [], []
    for blk, pk, pv in zip(weights["blocks"], ks, vs):
        x_s, kb, vb = _tp_layer(x_s, pk, pv, seq_pos, blk, arch, rope,
                                axis, tp, overlap, s=s)
        new_ks.append(kb)
        new_vs.append(vb)
    xf = _norm(x_s, weights["nfw"], weights["nfb"], arch["norm"],
               arch["eps"])
    head_l = weights["head"] if weights["head"] is not None else wte_l.T
    logits = allgather_matmul(xf, head_l, axis, tp, overlap=overlap)
    return (logits.reshape(b, s, logits.shape[-1]),
            new_ks, new_vs, seq_pos + s)


def build_tp_verify_program(model, mesh: Mesh, tp: int, *, width: int,
                            overlap: bool = True):
    """Build the fused verify program of the speculative-decoding path:
    ``fn(ks, vs, seq_pos, ids) -> (logits, new_ks, new_vs, new_pos)``
    with ``ids [num_slots, width]`` (each slot's last committed token
    followed by its zero-padded draft window) and ``logits [num_slots,
    width, vocab]`` vocab-sharded over the mesh.  NOT jitted — the
    engine wraps it with its matched-sampling acceptance tail in the
    single compiled verify step, so the program-set pin (ONE verify)
    holds the same way decode's does.

    Same shard_map family as ``build_tp_decode_program`` — identical
    bundle layout, identical in/out specs modulo the width axis, the
    layer bodies ARE ``_tp_layer`` — just at token width ``width``.
    There is no ``pallas_block`` variant: the Pallas decode block
    (kernels/decode_block_tp.py) is a single-token kernel, so the
    ``tp_fused_block`` engine path verifies through THIS program and
    keeps the Pallas block for its decode steps."""
    from ..distributed._jax_compat import shard_map
    from ..distributed.sharding_utils import put_global
    if width < 2:
        raise ValueError(f"verify width must be >= 2 (spec_k >= 1), "
                         f"got {width}")
    arch, weights = model.tp_decode_weights(tp)
    specs = _bundle_specs(weights)
    weights = jax.tree.map(
        lambda w, s: None if w is None
        else put_global(w, NamedSharding(mesh, s)),
        weights, specs, is_leaf=lambda x: x is None)
    num_layers = len(weights["blocks"])
    body = functools.partial(_tp_verify_body, arch=arch, tp=tp,
                             axis=TP_AXIS, overlap=overlap, width=width)
    slab = [KV_SLAB_SPEC] * num_layers

    def program(ks, vs, seq_pos, ids):
        return shard_map(
            body, mesh=mesh,
            in_specs=(specs, slab, slab, P(), P()),
            out_specs=(P(None, None, "mp"), slab, slab, P()),
            check_vma=False)(weights, ks, vs, seq_pos, ids)

    return program
