"""Drain-based decode-replica autoscaler for the fleet tier.

Production decode demand breathes: a burst fills every replica's queue,
a lull leaves silicon idle.  The :class:`Autoscaler` is the host-side
control loop that sizes the DECODE side of a (possibly disaggregated)
fleet against the live ``router.queue_depth`` gauge:

  * **scale up** — queue depth at or above ``scale_up_depth`` for
    ``hysteresis_steps`` CONSECUTIVE ticks spawns one decode replica via
    the caller's ``spawn_fn`` (a zero-arg factory returning a ready
    ``ServingEngine``).  The spawn is gated: the replica joins the
    router's rotation ONLY after the factory (and the optional
    ``warmup_fn``) returned successfully — a half-built replica is never
    routable, and a spawn failure (the ``replica_spawn`` chaos point, or
    a real construction error) leaves the router topology untouched;
  * **scale down** — queue depth at or below ``scale_down_depth`` with
    no fleet backlog for ``hysteresis_steps`` consecutive ticks retires
    one AUTOSCALED decode replica (never an operator-built one, never
    below ``min_decode`` decode-capable replicas) through the graceful
    two-phase path: ``router.drain(i)`` stops new work immediately, and
    once the replica reports ``drained`` it is closed and marked retired
    (``router.retire(i)``) — in-flight requests always finish normally;
  * **hysteresis + cooldown** — the consecutive-tick requirement plus a
    ``cooldown_steps`` refractory period after every action stop the
    loop from flapping on a noisy queue;
  * **straggler replacement** (``replace_slow_after``; docs/serving.md
    "Tail latency") — an AUTOSCALED decode replica the router's
    straggler detector has marked slow for that many consecutive fleet
    steps is replaced: graceful drain → retire, replacement spawned
    through the normal warmup gate, same cooldown as every other
    action.

``spawn``/``retire`` is a registered graftlint ``ResourcePair``
(receiver hint ``scaler``): an autoscaled replica must eventually retire
(or be explicitly kept), so capacity accounting cannot silently drift.
All state is host-side; ``tick()`` is called by ``Router.step()`` once
the autoscaler is attached (``Autoscaler(router, ...)`` attaches
itself).  Telemetry: ``autoscaler.*`` counters/gauges plus
``autoscaler_*`` events on the router's tracer lane
(docs/observability.md glossary).
"""

from __future__ import annotations

from typing import Callable, List, Optional

__all__ = ["Autoscaler"]


class Autoscaler:
    """Queue-depth-driven spawn/retire loop over a
    :class:`~paddle_tpu.serving.router.Router` (see module docstring).

    ``spawn_fn()`` must return a fresh ``ServingEngine`` built onto the
    ROUTER's shared registry/tracer (and, for token parity across the
    fleet, the same weights as its peers).  ``warmup_fn(engine)``, when
    given, runs after construction and before the replica becomes
    routable — use it to pre-trace programs so a spawned replica serves
    in steps, not compiles.  With ``aot_store`` given and a factory
    that accepts ``aot_store=``, warmup becomes a LOAD: every spawn
    (scale-up, resurrection, straggler replacement) hands the shared
    store to the factory so the replica deserializes its programs
    instead of tracing them (docs/serving.md "Zero cold start").
    """

    def __init__(self, router, spawn_fn: Callable, *,
                 warmup_fn: Optional[Callable] = None,
                 min_decode: int = 1, max_decode: int = 8,
                 scale_up_depth: int = 8, scale_down_depth: int = 0,
                 hysteresis_steps: int = 4, cooldown_steps: int = 16,
                 replace_slow_after: Optional[int] = None,
                 faults=None,
                 aot_store=None):
        if replace_slow_after is not None and replace_slow_after < 1:
            raise ValueError(
                "replace_slow_after must be >= 1 (or None to disable "
                "straggler replacement)")
        if min_decode < 1:
            raise ValueError("min_decode must be >= 1")
        if max_decode < min_decode:
            raise ValueError("max_decode must be >= min_decode")
        if scale_up_depth <= scale_down_depth:
            raise ValueError(
                "scale_up_depth must exceed scale_down_depth "
                "(overlapping thresholds would oscillate)")
        if hysteresis_steps < 1:
            raise ValueError("hysteresis_steps must be >= 1")
        self.router = router
        self.spawn_fn = spawn_fn
        self.warmup_fn = warmup_fn
        # zero-cold-start (serving/aot.py): when the fleet has a shared
        # AOT program store and the caller's factory can take it
        # (``spawn_fn(aot_store=...)``), every spawn — scale-up,
        # resurrection, straggler replacement — passes the store so the
        # new replica warm-loads its programs instead of compiling
        # under fleet load.  Zero-arg factories keep working unchanged.
        self.aot_store = aot_store
        if aot_store is not None:
            import inspect
            try:
                params = inspect.signature(spawn_fn).parameters
            except (TypeError, ValueError):
                params = {}
            self._spawn_takes_store = "aot_store" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values())
        else:
            self._spawn_takes_store = False
        self.min_decode = min_decode
        self.max_decode = max_decode
        self.scale_up_depth = scale_up_depth
        self.scale_down_depth = scale_down_depth
        self.hysteresis_steps = hysteresis_steps
        self.cooldown_steps = cooldown_steps
        # straggler replacement (docs/serving.md "Tail latency"): an
        # AUTOSCALED decode replica continuously marked slow by the
        # router's detector for this many fleet steps is replaced —
        # drain → retire through the normal graceful path, replacement
        # spawned through the normal warmup gate.  None disables;
        # operator-built replicas are never replaced (slow hardware an
        # operator placed deliberately is the operator's call).
        self.replace_slow_after = replace_slow_after
        self.faults = faults            # chaos hook: replica_spawn
        self._above = 0                 # consecutive ticks over the bar
        self._below = 0                 # consecutive idle ticks
        self._cooldown = 0
        self._spawned: List[int] = []   # replica indices this loop added
        self._retiring: List[int] = []  # draining, waiting to retire
        # killed replica indices whose replacement already spawned —
        # resurrection is per-victim so a dead PREFILL replica is
        # replaced in kind, not as yet another decode replica
        self._resurrected: set = set()
        m = router.metrics
        g, c = m.registry.gauge, m.registry.counter
        self._g_decode = g("autoscaler.decode_replicas",
                           "decode-capable replicas in rotation "
                           "(decode + unified, not draining/retired)")
        self._c_spawns = c("autoscaler.spawns",
                           "decode replicas spawned into the rotation")
        self._c_retires = c("autoscaler.retires",
                            "decode replicas retired via drain")
        self._c_spawn_failures = c(
            "autoscaler.spawn_failures",
            "replica spawns that failed before becoming routable "
            "(the half-built replica was never in rotation)")
        self._c_resurrections = c(
            "autoscaler.resurrections",
            "replacements spawned for KILLED replicas (Router.kill — "
            "crash resurrection through the normal warmup gate)")
        self._c_slow_replacements = c(
            "autoscaler.slow_replacements",
            "autoscaled decode replicas replaced for persistent "
            "straggling (drain -> retire -> spawn)")
        self._lane = m.lane             # events share the router's lane
        self._tracer = m.tracer
        self._publish()
        router.attach_autoscaler(self)

    # ------------------------------------------------------------- sizing
    def decode_count(self) -> int:
        """Decode-capable replicas currently in rotation."""
        return sum(1 for h in self.router.replicas
                   if h.role in ("decode", "unified")
                   and not h.draining and not h.retired)

    def _publish(self) -> None:
        self._g_decode.set(self.decode_count())

    # --------------------------------------------------------------- tick
    def tick(self) -> Optional[str]:
        """One control iteration (the router calls this after every
        fleet step).  Returns the action taken ("spawn" / "retire" /
        "resurrect" / "retired:<i>") or None — test and operator
        visibility."""
        action = self._finish_retirements()
        # replica RESURRECTION (docs/serving.md "Crash recovery"): a
        # killed replica is lost capacity, not queue noise — replace it
        # IN KIND (same role) through the same spawn/warmup gate,
        # ignoring hysteresis and cooldown (which exist to damp
        # flapping on a noisy queue, not to slow crash recovery).  A
        # failed spawn (replica_spawn chaos point) leaves the victim
        # unresurrected and the next tick retries.
        dead = [h for h in self.router.replicas
                if h.killed and h.index not in self._resurrected]
        for victim in dead:
            # max_decode bounds only the decode plane; a dead prefill
            # replica's replacement never counts against it — and a
            # decode-capped victim at the head of the list must not
            # starve later victims (a prefill replica especially)
            if victim.serves("decode") \
                    and self.decode_count() >= self.max_decode:
                continue
            if self.spawn(role=victim.role) is not None:
                self._resurrected.add(victim.index)
                self._c_resurrections.inc()
                self._tracer.event("autoscaler_resurrect",
                                   lane=self._lane,
                                   replica=victim.index,
                                   role=victim.role)
                return "resurrect"
            break       # spawn failed: retry next tick, no spawn storm
        if self._cooldown > 0:
            self._cooldown -= 1
            return action
        # straggler replacement (docs/serving.md "Tail latency"): an
        # autoscaled replica persistently marked slow is retired
        # through the normal graceful drain and its capacity respawned
        # at once — subject to the same cooldown as every other action
        # so one bad replica cannot start a churn storm
        if self.replace_slow_after is not None:
            victim = next(
                (self.router.replicas[i] for i in self._spawned
                 if not self.router.replicas[i].draining
                 and not self.router.replicas[i].retired
                 and self.router.replicas[i].slow_ticks
                 >= self.replace_slow_after), None)
            if victim is not None:
                # spawn the replacement FIRST: a failed spawn must not
                # shrink the fleet (slow capacity beats absent capacity
                # and min_decode must hold) — the victim keeps serving
                # and a post-cooldown tick retries.  The cooldown is
                # taken on BOTH outcomes: a persistently failing
                # spawn_fn must not be re-run (model build + warmup)
                # on every fleet step.  The one-tick overshoot of
                # max_decode resolves when the retire's drain starts
                # (a draining replica leaves decode_count immediately).
                self._cooldown = self.cooldown_steps
                if self.spawn() is None:
                    return action
                self.retire(victim.index)
                self._c_slow_replacements.inc()
                self._tracer.event("autoscaler_replace_slow",
                                   lane=self._lane,
                                   replica=victim.index,
                                   slow_ticks=victim.slow_ticks)
                return "replace_slow"
        depth = self.router.queue_depth
        self._above = self._above + 1 if depth >= self.scale_up_depth \
            else 0
        idle = depth <= self.scale_down_depth \
            and self.router.in_flight == 0
        self._below = self._below + 1 if idle else 0
        if self._above >= self.hysteresis_steps \
                and self.decode_count() < self.max_decode:
            self._above = 0
            self._cooldown = self.cooldown_steps
            return "spawn" if self.spawn() is not None else action
        if self._below >= self.hysteresis_steps and self._spawned \
                and self.decode_count() > self.min_decode:
            self._below = 0
            self._cooldown = self.cooldown_steps
            victim = self._pick_victim()
            if victim is not None:
                self.retire(victim)
                return "retire"
        return action

    def _finish_retirements(self) -> Optional[str]:
        """Close out replicas whose drain completed (second phase of
        retire)."""
        done = None
        for idx in list(self._retiring):
            if self.router.replicas[idx].retired:
                # killed (or otherwise force-removed) while draining:
                # the handle already left the fleet — nothing to close
                self._retiring.remove(idx)
                continue
            if self.router.drained(idx):
                self._retiring.remove(idx)
                self.router.retire(idx)
                self._publish()
                self._tracer.event("autoscaler_retired", lane=self._lane,
                                   replica=idx)
                done = f"retired:{idx}"
        return done

    def _pick_victim(self) -> Optional[int]:
        """Lightest-loaded autoscaled decode replica still in
        rotation."""
        live = [self.router.replicas[i] for i in self._spawned
                if not self.router.replicas[i].draining
                and not self.router.replicas[i].retired]
        if not live:
            return None
        return min(live, key=lambda h: (h.load, h.index)).index

    # ------------------------------------------------------ spawn/retire
    def spawn(self, role: str = "decode") -> Optional[int]:
        """Build one replica (``role`` defaults to the scaling loop's
        decode plane; resurrection passes the dead replica's role so a
        prefill victim is replaced in kind) and add it to the rotation;
        returns its replica index, or None when the spawn failed (the
        router is then untouched — a half-built replica is never
        routable).  Balance with :meth:`retire` over the replica's life
        (registered graftlint ``ResourcePair``)."""
        engine = None
        try:
            if self.faults is not None:
                self.faults.fire("replica_spawn")
            if self._spawn_takes_store:
                engine = self.spawn_fn(aot_store=self.aot_store)
            else:
                engine = self.spawn_fn()
            if self.warmup_fn is not None:
                self.warmup_fn(engine)
        except Exception as e:
            if engine is not None:
                # the factory succeeded but the warmup raised: the
                # half-built engine already bound telemetry (tracer
                # lanes, possibly a profiler source) — close it or a
                # long-running server accumulates dead lanes per
                # failed spawn
                try:
                    engine.close()
                except Exception:
                    pass
            self._c_spawn_failures.inc()
            self._tracer.event("autoscaler_spawn_failed", lane=self._lane,
                               error=repr(e)[:200])
            return None
        idx = self.router.add_replica(engine, role=role)
        if role != "prefill":
            # scale-down only ever retires decode-capable autoscaled
            # replicas — a resurrected prefill replica must never be
            # picked as an idle-retirement victim
            self._spawned.append(idx)
        self._c_spawns.inc()
        self._publish()
        self._tracer.event("autoscaler_spawn", lane=self._lane,
                           replica=idx, role=role)
        return idx

    def retire(self, replica: int) -> None:
        """Begin the graceful retirement of ``replica``: drain it now
        (no new work), close + mark retired once its in-flight work
        finishes (a later :meth:`tick` completes the second phase)."""
        self.router.drain(replica)
        self._retiring.append(replica)
        if replica in self._spawned:
            self._spawned.remove(replica)
        self._c_retires.inc()
        self._publish()
        self._tracer.event("autoscaler_retire", lane=self._lane,
                           replica=replica)

    # -------------------------------------------------------------- state
    def snapshot(self) -> dict:
        return {
            "decode_replicas": self.decode_count(),
            "spawned": list(self._spawned),
            "retiring": list(self._retiring),
            "cooldown": self._cooldown,
            "spawns": self._c_spawns.value,
            "retires": self._c_retires.value,
            "spawn_failures": self._c_spawn_failures.value,
            "resurrections": self._c_resurrections.value,
            "resurrected_victims": sorted(self._resurrected),
            "slow_replacements": self._c_slow_replacements.value,
        }
