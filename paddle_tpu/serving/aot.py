"""Zero-cold-start AOT program store (docs/serving.md "Zero cold start").

The compile-surface manifest (PR 16, tools/analysis/compile_surface.py)
statically PROVES the serving engine's program set — ``{chunk} +
O(log2) prefill buckets + ONE decode + 1 gather + 1 scatter`` per
device plane.  This module turns that proof into a build input: the
builder AOT-lowers every manifest program on the ``EngineCore`` plane
through ``jit/_export_compat`` (jax.export) and persists the serialized
artifacts into an on-disk store; ``EngineCore(aot_store=...)`` then
LOADS instead of traces on startup, so an autoscaler spawn, a
resurrection or a quarantine rebuild is routable without paying a
single trace.

Store layout (one directory)::

    <store>/
      index.json          # atomic publish point: fingerprint + entries
      objects/<sha>.aot   # CRC-framed serialized jax.export artifacts

Framing and publish discipline mirror the request journal
(serving/journal.py): each object is one ``<u32 len><u32 crc32>``
frame, and the index lands via tmp-write + fsync + ``os.replace`` — a
crash mid-build leaves unreferenced objects (``aot_build.py gc``
collects them), never a half-published store.

Keying: the store carries ONE fingerprint — a sha256 over the
canonicalized (model config, serving config, tensor-parallel degree,
jax/jaxlib versions) tuple — and per-program entries named by their
manifest counter plus key-space leg (``prefill:w<width>`` per committed
bucket width, ``decode:<resolved path>``, ``gather``, ``scatter``).  An
engine whose fingerprint differs, or whose resolved leg is absent,
falls back loudly-but-gracefully to tracing (an ``aot_miss`` /
``aot_fallback`` degradation event, never a crash).  The writer refuses
to publish a store missing any manifest program id or holding a
program the manifest classifies unbounded — the completeness check the
manifest gives us for free.

Lifecycle (registered graftlint ResourcePairs): readers pair
``AOTStore.open`` with ``close``; builders pair ``AOTStore.create``
with ``publish`` (success) or ``discard`` (abort) on every path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import struct
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..jit import _export_compat as _jx

__all__ = ["AOTStore", "AOTStoreWriter", "AOTStoreError",
           "build_engine_store", "engine_aot_context", "aot_fingerprint"]

# graftprog: the store's deserialize + jit re-wrap and the builder's
# export path are compile-surface units — the builder function and the
# reader class are their entry points (the engine reaches them only
# through a stored handle, which the static walk cannot follow)
__compile_surface_roots__ = ("build_engine_store", "AOTStore")

# version 2: the decode signature gained the constrained-decoding vocab
# mask operand and the plane gained the ONE verify program (ISSUE 18) —
# a version-1 store's decode artifact would be called with an operand it
# was never exported for, so open() refuses old stores outright instead
# of letting the mismatch surface as a shape error mid-serve
STORE_VERSION = 2
INDEX_NAME = "index.json"
OBJECTS_DIR = "objects"
ENGINE_PLANE = "paddle_tpu.serving.engine.EngineCore"

# journal-style CRC framing: (payload_len, crc32(payload)) prefix.  The
# length guard rejects garbage headers before a huge allocation.
_HEADER = struct.Struct("<II")
_MAX_PAYLOAD = 1 << 30


class AOTStoreError(RuntimeError):
    """A store-contract violation: unpublished/corrupt store, missing
    manifest coverage at publish, or builder/runtime bucket drift."""


# --------------------------------------------------------------- keying
def _canon(obj: Any) -> Any:
    """Canonical JSON-safe form: dicts sort, tuples become lists, and
    anything non-primitive (dtypes, enums) stringifies — the fingerprint
    must not depend on dict order or repr jitter."""
    if isinstance(obj, dict):
        return {str(k): _canon(obj[k]) for k in sorted(obj, key=str)}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    return str(obj)


def aot_fingerprint(context: Dict[str, Any]) -> str:
    """Deterministic store key: sha256 over the canonicalized context."""
    blob = json.dumps(_canon(context), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def engine_aot_context(core) -> Dict[str, Any]:
    """The fingerprint preimage for one engine: everything that shapes
    a compiled program — model config, the RESOLVED serving geometry
    (pool max_seq, block_len, num_blocks — not the constructor args),
    tensor-parallel degree and the jax/jaxlib versions the artifacts
    were lowered under.  The decode path is deliberately NOT here: it
    keys the per-program leg (``decode:<path>``), so a fused and an
    unfused engine share one store fingerprint."""
    cfg = core.model.cfg
    model_ctx = dataclasses.asdict(cfg) if dataclasses.is_dataclass(cfg) \
        else dict(vars(cfg))
    try:
        import jaxlib.version as _jlv
        jaxlib_version = _jlv.__version__
    except Exception:
        jaxlib_version = "unknown"
    bp = core.block_pool
    return {
        "store_version": STORE_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "model_class": type(core.model).__name__,
        "model": model_ctx,
        "num_slots": core.num_slots,
        "max_seq": core.pool.max_seq,
        "min_bucket": core.scheduler.min_bucket,
        "prefill_chunk": core.prefill_chunk,
        "block_len": bp.block_len if bp is not None else None,
        "num_blocks": bp.num_blocks if bp is not None else None,
        "tensor_parallel": core.tensor_parallel,
        # the RESOLVED speculative window (0 when speculation was not
        # requested or not viable): it shapes the verify program's
        # [num_slots, spec_k+1] operands, so a spec_k=2 engine must not
        # warm-load a spec_k=4 store's verify artifact
        "spec_k": core.spec_k if core.spec_on else 0,
    }


def _wrap_call(exported, donate: Tuple[int, ...], mesh=None) -> Callable:
    """Re-wrap a deserialized program as a dispatchable callable.
    Executing ``exported.call`` never re-traces the original Python
    body (so no trace counter can tick); the jit wrapper restores the
    donation contract the traced program had, keeping pool memory a
    single allocation on the warm path too.

    ``mesh``: programs exported for an N-device mesh refuse to run when
    any operand lives on fewer devices ("exported for N devices and is
    called in a context with 1"), and the engine's host-built operands
    (token ids, positions, sampling knobs) are exactly that.  The shim
    replicates any operand not already spanning the mesh; the sharded
    slabs (which include every donated operand) pass through untouched,
    so donation still lands on the real buffers."""
    if donate:
        fn = jax.jit(exported.call, donate_argnums=donate)
    else:
        fn = jax.jit(exported.call)
    if mesh is None or mesh.size <= 1:
        return fn
    from .tp import replicated

    def call(*args):
        placed = tuple(
            a if (isinstance(a, jax.Array)
                  and len(a.sharding.device_set) == mesh.size)
            else replicated(a, mesh)
            for a in args)
        return fn(*placed)

    return call


# ---------------------------------------------------------------- store
class AOTStore:
    """Reader handle over a PUBLISHED store directory.

    Pure host state plus lazy artifact reads; share one instance across
    every engine in a fleet (loads are independent).  Pair ``open`` with
    ``close`` (registered ResourcePair).  ``faults`` is the chaos hook:
    ``aot_store_corrupt`` fires inside the CRC read path so the suite
    can prove a rotted artifact degrades the engine to tracing."""

    def __init__(self, path: str, index: Dict[str, Any], faults=None):
        self.path = path
        self._index = index
        self.faults = faults
        self._closed = False

    # ------------------------------------------------------- lifecycle
    @classmethod
    def open(cls, path: str, faults=None) -> "AOTStore":
        """Open a published store.  Raises :class:`AOTStoreError` when
        no index was ever published (a crashed build leaves objects but
        no index — that is the atomicity contract, not corruption)."""
        index_path = os.path.join(path, INDEX_NAME)
        if not os.path.exists(index_path):
            raise AOTStoreError(
                f"no published AOT store at {path!r} (missing "
                f"{INDEX_NAME}; a build that crashed before publish "
                f"leaves no index)")
        try:
            with open(index_path, "r", encoding="utf-8") as f:
                index = json.load(f)
        except (OSError, ValueError) as e:
            raise AOTStoreError(
                f"unreadable AOT store index at {index_path!r}: "
                f"{e!r}") from e
        if index.get("version") != STORE_VERSION:
            raise AOTStoreError(
                f"AOT store version skew: index version "
                f"{index.get('version')!r}, runtime expects "
                f"{STORE_VERSION}")
        return cls(path, index, faults=faults)

    def close(self) -> None:
        """Release the handle (idempotent; loads after close raise)."""
        self._closed = True

    # --------------------------------------------------------- queries
    @property
    def fingerprint(self) -> str:
        return self._index.get("fingerprint", "")

    @property
    def widths(self) -> Tuple[int, ...]:
        """The committed prefill bucket-width set recorded at build."""
        return tuple(self._index.get("widths", ()))

    @property
    def context(self) -> Dict[str, Any]:
        return dict(self._index.get("context", {}))

    @property
    def build_seconds(self) -> float:
        """Total builder export time across artifacts (observability:
        the ``aot.build_s`` gauge an attaching engine republishes)."""
        return float(sum(e.get("build_s", 0.0)
                         for e in self._index.get("programs", {}).values()))

    def programs(self) -> Dict[str, Dict[str, Any]]:
        return dict(self._index.get("programs", {}))

    def has(self, name: str) -> bool:
        return name in self._index.get("programs", {})

    # ----------------------------------------------------------- loads
    def load(self, name: str):
        """Deserialize program ``name`` (CRC-verified).  Raises
        :class:`AOTStoreError` on a missing entry or corrupt artifact —
        the ENGINE turns that into a degradation event, never a crash."""
        if self._closed:
            raise AOTStoreError("AOT store handle is closed")
        entry = self._index.get("programs", {}).get(name)
        if entry is None:
            raise AOTStoreError(
                f"program {name!r} not in store index (have: "
                f"{sorted(self._index.get('programs', {}))})")
        payload = self._read_object(entry["object"])
        try:
            return _jx.deserialize(bytearray(payload))
        except Exception as e:
            raise AOTStoreError(
                f"artifact {name!r} failed to deserialize (jax/jaxlib "
                f"skew?): {e!r}") from e

    def load_call(self, name: str, donate: Sequence[int] = (),
                  mesh=None) -> Callable:
        """:meth:`load` + the donation-restoring jit re-wrap — what the
        engine installs as its program handle.  Pass the engine's mesh
        for tensor-parallel programs so host-built operands are
        replicated up to the export's device count (see
        :func:`_wrap_call`)."""
        return _wrap_call(self.load(name), tuple(donate), mesh=mesh)

    def _read_object(self, obj: str) -> bytes:
        path = os.path.join(self.path, OBJECTS_DIR, obj + ".aot")
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise AOTStoreError(
                f"artifact object {obj!r} unreadable: {e!r}") from e
        if self.faults is not None:
            # chaos: pretend the frame rotted — same code path a real
            # flipped bit takes below
            if self.faults.check("aot_store_corrupt") is not None:
                raise AOTStoreError(
                    f"artifact object {obj!r} corrupt (injected)")
        if len(raw) < _HEADER.size:
            raise AOTStoreError(
                f"artifact object {obj!r} truncated ({len(raw)} bytes)")
        n, crc = _HEADER.unpack_from(raw)
        if n > _MAX_PAYLOAD or len(raw) != _HEADER.size + n:
            raise AOTStoreError(
                f"artifact object {obj!r} corrupt: framed length {n}, "
                f"file holds {len(raw) - _HEADER.size} payload bytes")
        payload = raw[_HEADER.size:]
        if zlib.crc32(payload) != crc:
            raise AOTStoreError(
                f"artifact object {obj!r} corrupt: CRC mismatch")
        return payload

    # ------------------------------------------------------- authoring
    @classmethod
    def create(cls, path: str, *, context: Dict[str, Any],
               plane: Dict[str, Any],
               widths: Sequence[int]) -> "AOTStoreWriter":
        """Start a build into ``path``.  Pair with ``publish()`` on
        success or ``discard()`` on every abort path (registered
        ResourcePair) — nothing is visible to readers until publish."""
        return AOTStoreWriter(path, context=context, plane=plane,
                              widths=widths)


class AOTStoreWriter:
    """One in-flight build: content-addressed objects land immediately
    (a crash leaves only unreferenced garbage), the index lands whole
    at :meth:`publish` — tmp-write + fsync + ``os.replace``, the
    journal's torn-tail discipline applied to a single file."""

    def __init__(self, path: str, *, context: Dict[str, Any],
                 plane: Dict[str, Any], widths: Sequence[int]):
        self.path = path
        self.context = _canon(context)
        self.fingerprint = aot_fingerprint(context)
        self.plane = plane
        self.widths = tuple(int(w) for w in widths)
        self._programs: Dict[str, Dict[str, Any]] = {}
        self._written: List[str] = []
        self._done = False
        os.makedirs(os.path.join(path, OBJECTS_DIR), exist_ok=True)

    def add(self, name: str, exported, *, build_s: float = 0.0) -> None:
        """Serialize + CRC-frame one program under leg key ``name``
        (``prefill:w<width>`` / ``decode:<path>`` / ``gather`` /
        ``scatter``)."""
        if self._done:
            raise AOTStoreError("writer already published/discarded")
        payload = bytes(exported.serialize())
        obj = hashlib.sha256(payload).hexdigest()
        obj_path = os.path.join(self.path, OBJECTS_DIR, obj + ".aot")
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        tmp = obj_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, obj_path)
        self._written.append(obj_path)
        counter = name.split(":", 1)[0]
        manifest_ids = list(self.plane.get(counter, {}).get("programs",
                                                           []))
        self._programs[name] = {
            "object": obj,
            "bytes": len(payload),
            "counter": counter,
            "manifest_programs": manifest_ids,
            "build_s": round(float(build_s), 6),
        }

    def _missing(self) -> List[str]:
        """Manifest program ids (by counter leg) the build has not
        covered — publish refuses while this is non-empty."""
        covered = {e["counter"] for e in self._programs.values()}
        missing: List[str] = []
        for counter in sorted(self.plane):
            if counter == "prefill":
                for w in self.widths:
                    if f"prefill:w{w}" not in self._programs:
                        missing.append(f"prefill:w{w}")
            elif counter == "decode":
                if not any(n.startswith("decode:")
                           for n in self._programs):
                    missing.append("decode:<path>")
            elif counter == "verify":
                # the STATIC plane always carries the verify counter
                # (the program exists in the source), but a spec_k=0
                # build has no verify program to export — completeness
                # is keyed on the store's resolved spec_k
                if not self.context.get("spec_k"):
                    continue
                if not any(n.startswith("verify:")
                           for n in self._programs):
                    missing.append("verify:<path>")
            elif counter not in covered:
                missing.append(counter)
        return missing

    def publish(self) -> Dict[str, Any]:
        """Completeness-check against the manifest plane, then publish
        atomically.  Refuses (store stays unpublished) when any manifest
        program id is missing or the manifest classifies a plane program
        unbounded — an unbounded key space cannot be enumerated, so an
        AOT store over it would be a lie."""
        if self._done:
            raise AOTStoreError("writer already published/discarded")
        for counter, entry in sorted(self.plane.items()):
            if entry.get("key_space") == "unbounded":
                raise AOTStoreError(
                    f"refusing to publish: manifest classifies "
                    f"{counter!r} UNBOUNDED ({entry.get('programs')}); "
                    f"an unbounded program set cannot be AOT-enumerated")
        missing = self._missing()
        if missing:
            raise AOTStoreError(
                f"refusing to publish: store misses manifest programs "
                f"{missing} (plane counters: {sorted(self.plane)}, "
                f"committed widths: {list(self.widths)})")
        index = {
            "version": STORE_VERSION,
            "fingerprint": self.fingerprint,
            "context": self.context,
            "widths": list(self.widths),
            "plane": {c: {"upper_bound": e.get("upper_bound"),
                          "key_space": e.get("key_space"),
                          "programs": list(e.get("programs", []))}
                      for c, e in sorted(self.plane.items())},
            "programs": self._programs,
            "built_unix": round(time.time(), 3),
        }
        index_path = os.path.join(self.path, INDEX_NAME)
        tmp = index_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(index, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, index_path)
        self._done = True
        return index

    def discard(self) -> None:
        """Abort: drop every object this writer wrote (idempotent).  A
        previously published index — if this was a rebuild into an
        existing store — is left untouched."""
        self._done = True
        for p in self._written:
            try:
                os.remove(p)
            except OSError:
                pass
        self._written = []


# -------------------------------------------------------------- builder
def _default_manifest() -> Dict[str, Any]:
    """The same manifest the CLI's ``graftlint --manifest`` emits,
    built through the shared library entry point over the repo scope."""
    from ..tools.analysis import build_manifest_for_paths
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    scope = [os.path.join(root, p)
             for p in ("paddle_tpu", "bench.py", "scripts")]
    return build_manifest_for_paths(scope, root=root)


def _on_mesh(core, x):
    """Replicate a host-built example arg onto the engine's mesh so a
    tensor-parallel export sees the same device assignment the sharded
    slabs carry (single-chip engines pass through)."""
    if core.mesh is None:
        return x
    from .tp import replicated
    return replicated(x, core.mesh)


def _staging_example(core):
    """Example prefill staging rows, built through the SAME compiled
    zero-staging program shape ``_begin_prefill`` uses — identical
    shapes, dtypes and (under tp) shardings to the runtime operands."""
    model, max_seq = core.model, core.pool.max_seq

    def fresh_staging():
        caches = model.init_cache(1, max_seq)
        return [c[0] for c in caches], [c[1] for c in caches]

    with core._mesh_scope():
        return jax.jit(fresh_staging)()


def _export_programs(core, writer: AOTStoreWriter) -> None:
    """Trace + AOT-lower the full manifest program set of ``core``:
    one prefill per committed bucket width, the ONE decode at the
    resolved path, the gather and the scatter.  Example operands are
    the engine's real device state (plus replicated host scalars), so
    exported shardings match what the runtime will pass."""
    ks, vs = _staging_example(core)
    prefill = core._build_prefill_fn()
    pos = _on_mesh(core, jnp.asarray(0, jnp.int32))
    for w in writer.widths:
        t0 = time.perf_counter()
        ids = _on_mesh(core, jnp.zeros((1, w), jnp.int32))
        with core._mesh_scope():
            exported = _jx.export(prefill)(ks, vs, ids, pos, pos)
        writer.add(f"prefill:w{w}", exported,
                   build_s=time.perf_counter() - t0)

    t0 = time.perf_counter()
    decode = core._build_decode_fn()
    n = core.num_slots
    vocab = int(core.model.cfg.vocab_size)
    sampling = (_on_mesh(core, jnp.tile(jax.random.PRNGKey(0)[None],
                                        (n, 1))),
                _on_mesh(core, jnp.zeros((n,), bool)),
                _on_mesh(core, jnp.ones((n,), jnp.float32)),
                _on_mesh(core, jnp.zeros((n,), jnp.int32)),
                _on_mesh(core, jnp.ones((n,), jnp.float32)),
                _on_mesh(core, jnp.ones((n, vocab), bool)))
    args = (core.pool.ks, core.pool.vs, core.pool.seq_pos,
            _on_mesh(core, jnp.zeros((n,), jnp.int32)),
            *sampling)
    with core._mesh_scope():
        exported = _jx.export(decode)(*args)
    writer.add(f"decode:{core.decode_path}", exported,
               build_s=time.perf_counter() - t0)

    if core.spec_on:
        # the ONE verify program: same operands as decode plus the
        # fixed-shape draft window (the engine keys the leg on the
        # decode path, exactly like decode itself)
        t0 = time.perf_counter()
        verify = core._build_verify_fn()
        vargs = args + (
            _on_mesh(core, jnp.zeros((n, core.spec_k), jnp.int32)),
            _on_mesh(core, jnp.zeros((n,), jnp.int32)))
        with core._mesh_scope():
            exported = _jx.export(verify)(*vargs)
        writer.add(f"verify:{core.decode_path}", exported,
                   build_s=time.perf_counter() - t0)

    bp = core.block_pool
    idx = _on_mesh(core, jnp.zeros((bp.blocks_per_row,), jnp.int32))
    t0 = time.perf_counter()
    with core._mesh_scope():
        exported = _jx.export(bp._build_load_fn())(bp.bks, bp.bvs, idx)
    writer.add("gather", exported, build_s=time.perf_counter() - t0)
    t0 = time.perf_counter()
    slot = _on_mesh(core, jnp.asarray(0, jnp.int32))
    with core._mesh_scope():
        exported = _jx.export(bp._build_store_fn())(
            bp.bks, bp.bvs, core.pool.ks, core.pool.vs, slot, idx)
    writer.add("scatter", exported, build_s=time.perf_counter() - t0)


def build_engine_store(path: str, core,
                       manifest: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    """Build + publish an AOT store for ``core``'s configuration.

    ``core`` is a constructed (cold is fine — the build IS its trace)
    :class:`~paddle_tpu.serving.engine.EngineCore`; ``manifest`` is the
    graftprog manifest dict (``scripts/graftlint.py --manifest`` output
    or :func:`build_manifest_for_paths` — rebuilt over the repo scope
    when omitted).  The builder engine must have its prefix cache
    enabled: the manifest plane holds the gather/scatter programs, and
    publish refuses an incomplete store.  Returns the published index.
    """
    if manifest is None:
        manifest = _default_manifest()
    plane = manifest.get("planes", {}).get(ENGINE_PLANE)
    if plane is None:
        raise AOTStoreError(
            f"manifest has no {ENGINE_PLANE} plane (planes: "
            f"{sorted(manifest.get('planes', {}))})")
    if core.block_pool is None:
        raise AOTStoreError(
            "builder engine has no prefix-cache block pool; the "
            "manifest plane includes the gather/scatter programs, so "
            "build with enable_prefix_cache=True")
    writer = AOTStore.create(path, context=engine_aot_context(core),
                             plane=plane, widths=core.warm_buckets())
    try:
        _export_programs(core, writer)
        return writer.publish()
    except BaseException:
        writer.discard()
        raise
