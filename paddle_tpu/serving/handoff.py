"""Fault-tolerant KV handoff between fleet replicas.

The disaggregated fleet (docs/serving.md "Disaggregated fleet") splits
replicas into PREFILL and DECODE roles: a prefill replica runs a long
prompt's prefill, and the prompt's radix blocks then MOVE to the chosen
decode replica so its admission re-prefills only the tail.  The hard
part is not the copy — it is surviving a fault at any point of the
transfer without leaking a block, a pool slot, or a radix pin on either
replica.  This module is that guarantee, written as an explicit state
machine:

    staged ──► in_flight ──► committed
       │            │
       └────────────┴──────► aborted

  * **staged** — the prompt's block path is matched + PINNED on the
    source replica (``EngineCore.export_prompt_kv``); pinned blocks
    cannot be LRU-evicted, so the staged window may safely wait for a
    free staging slot on the destination;
  * **in_flight** — one transfer attempt: the source's gather program
    reads the pinned blocks into staging rows
    (``EngineCore.export_gather`` — THE compiled gather), and the
    destination adopts them through a transient pool slot into its own
    radix tree (``EngineCore.adopt_prompt_kv`` — the slot-adopt copy +
    THE compiled scatter).  No new compiled programs: the handoff rides
    the exact {gather, scatter, adopt} surface admission already uses;
  * **committed** — the destination owns the blocks; the source pin is
    released (its copies stay cached and evictable, warming future
    traffic on the source too);
  * **aborted** — any-stage failure: the source pin is released, the
    destination's transient slot was already returned by
    ``adopt_prompt_kv``'s own unwinding, and the router falls back to
    RE-PREFILLING on the decode side (or terminal failure when nothing
    can serve) — correctness never depends on a transfer landing.

Deterministic chaos (serving/faults.py, ROUTER-level injector): the
``handoff_gather`` / ``handoff_scatter`` / ``handoff_commit`` points
fire at the three stage boundaries; ``tests/test_zz_disagg_serving.py``
pins the total-accounting invariant for each.  ``stage`` /
``commit``-or-``abort`` is a registered graftlint ``ResourcePair``
(receiver hint ``handoff``): a staged record that reaches neither
terminal state is a leaked pin, and the lint gate proves callers close
the window on every path.

The manager is pure host-side control plane owned by
``serving.router.Router`` — it never steps an engine and adds nothing
to any hot path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["Handoff", "HandoffManager", "HANDOFF_STATES"]

STAGED = "staged"
IN_FLIGHT = "in_flight"
COMMITTED = "committed"
ABORTED = "aborted"
HANDOFF_STATES = (STAGED, IN_FLIGHT, COMMITTED, ABORTED)


class Handoff:
    """One prompt's transfer record: which replica pinned what, where it
    is going, and which terminal state it reached."""

    __slots__ = ("fleet_id", "src", "dst", "state", "tokens",
                 "blocks_moved", "transfer_attempts", "deferred_steps",
                 "reason", "_match", "_src_core")

    def __init__(self, fleet_id: int, src: int, match, src_core,
                 tokens: int):
        self.fleet_id = fleet_id
        self.src = src                  # source replica index
        self.dst = -1                   # chosen destination (set in flight)
        self.state = STAGED
        self.tokens = tokens            # pinned prefix length (tokens)
        self.blocks_moved = 0
        self.transfer_attempts = 0
        self.deferred_steps = 0         # staged scans spent waiting
        self.reason: Optional[str] = None    # why aborted (None else)
        self._match = match             # pinned MatchResult (or None)
        # the exact core whose tree holds the pin: if the source
        # quarantines mid-handoff its device plane (and radix tree) is
        # REBUILT — comparing against the live core detects that the
        # pinned path no longer exists and the transfer must abort
        self._src_core = src_core

    @property
    def terminal(self) -> bool:
        return self.state in (COMMITTED, ABORTED)

    def src_plane_alive(self) -> bool:
        """False once the source replica rebuilt its device plane (the
        pinned nodes belong to a discarded tree — gathering through
        their stale block ids would move garbage KV).  A rebuild that
        left NO cache at all (quarantine with the prefix cache
        ladder-bypassed sets ``prefix_cache = None``) is equally dead —
        it must not read as alive via a ``None is None`` comparison."""
        if self._match is None:
            return True
        cache = self._src_core.prefix_cache
        if cache is None:
            return False
        nodes = self._match._nodes
        if not nodes:
            return True                  # empty pin: nothing to gather
        # walk up to the root the pinned path hangs off; compare trees
        node = nodes[0]
        while node.parent is not None:
            node = node.parent
        return cache.root is node

    def __repr__(self) -> str:
        return (f"Handoff({self.fleet_id}, {self.src}->{self.dst}, "
                f"{self.state}, tokens={self.tokens}, "
                f"blocks={self.blocks_moved})")


class HandoffManager:
    """Owns every live :class:`Handoff` and the stage/transfer/commit/
    abort transitions.  The router decides WHEN to call each transition
    and with which replicas; this class guarantees the resource
    accounting — pin released exactly once, destination slot never
    leaked, every record terminal."""

    def __init__(self, faults=None, stage_patience: int = 16,
                 max_transfer_retries: int = 1):
        # chaos hook: the ROUTER's injector (serving/faults.py) — None
        # in production, zero overhead when unset
        self.faults = faults
        # staged scans to wait for a destination staging slot before
        # giving up on the transfer (the pin holds meanwhile)
        self.stage_patience = stage_patience
        self.max_transfer_retries = max_transfer_retries
        self.records: Dict[int, Handoff] = {}     # fleet_id -> live record
        # lifetime counters (the router mirrors them into obs)
        self.staged = 0
        self.committed = 0
        self.aborted = 0
        self.retries = 0
        self.blocks_moved = 0

    # ----------------------------------------------------------- staging
    def stage(self, fleet_id: int, src_handle, prompt) -> Handoff:
        """Open a handoff: pin ``prompt``'s cached path on the source
        replica and record the staged window.  Balance with
        :meth:`commit` or :meth:`abort` on every path (registered
        graftlint ``ResourcePair``)."""
        core = src_handle.engine.core
        match = core.export_prompt_kv(prompt)
        rec = Handoff(fleet_id, src_handle.index, match, core,
                      0 if match is None else match.tokens)
        self.records[fleet_id] = rec
        self.staged += 1
        return rec

    # ---------------------------------------------------------- transfer
    def transfer(self, rec: Handoff, src_handle, dst_handle,
                 prompt) -> bool:
        """One in-flight transfer attempt toward ``dst_handle``.
        Returns True on success (caller then :meth:`commit`\\ s); False
        when this attempt failed but a retry remains (the record drops
        back to ``staged``, pin still held).  Raises nothing: terminal
        failures abort internally and ALSO return False with
        ``rec.state == 'aborted'`` — the caller routes on the state."""
        if rec.terminal:
            raise RuntimeError(f"transfer on terminal handoff {rec!r}")
        rec.state = IN_FLIGHT
        rec.dst = dst_handle.index
        rec.transfer_attempts += 1
        try:
            if rec.tokens == 0:
                return True         # nothing cached: trivially complete
            if not rec.src_plane_alive():
                # the source quarantined mid-handoff: its rebuilt tree no
                # longer holds the pinned path — a retry can never
                # succeed, fall straight to the re-prefill recovery
                raise RuntimeError(
                    "source replica rebuilt its device plane mid-handoff "
                    "(pinned blocks discarded)")
            if self.faults is not None:
                self.faults.fire("handoff_gather")
            ks, vs = src_handle.engine.core.export_gather(rec._match)
            # handoff_scatter fires INSIDE adopt_prompt_kv, after the
            # destination's staging slot is claimed — the injected
            # fault genuinely proves the transient slot unwinds
            moved = dst_handle.engine.core.adopt_prompt_kv(
                prompt, ks, vs, rec.tokens, faults=self.faults)
            rec.blocks_moved = moved
            self.blocks_moved += moved
            return True
        except Exception as e:
            retryable = rec.src_plane_alive() \
                and rec.transfer_attempts <= self.max_transfer_retries
            if retryable:
                self.retries += 1
                rec.state = STAGED      # pin held; the next scan retries
                return False
            self.abort(rec, f"transfer failed: {e!r}")
            return False

    # ---------------------------------------------------------- terminal
    def commit(self, rec: Handoff) -> None:
        """Seal a successful transfer: the destination owns the blocks,
        the source pin is released (``handoff_commit`` chaos point fires
        BEFORE the release, so an injected commit fault exercises the
        abort path's pin unwinding with blocks already moved)."""
        if rec.terminal:
            return
        if self.faults is not None:
            self.faults.fire("handoff_commit")
        self._release(rec)
        rec.state = COMMITTED
        self.committed += 1
        del self.records[rec.fleet_id]

    def abort(self, rec: Handoff, reason: str) -> None:
        """Terminal failure of the transfer: release the source pin and
        record why.  Idempotent.  The destination's transient slot was
        already unwound by ``adopt_prompt_kv``'s own try/finally; any
        blocks that DID land on the destination are owned by its radix
        tree (evictable, fully accounted) — an aborted handoff leaks
        nothing on either replica."""
        if rec.terminal:
            return
        self._release(rec)
        rec.state = ABORTED
        rec.reason = reason
        self.aborted += 1
        self.records.pop(rec.fleet_id, None)

    def _release(self, rec: Handoff) -> None:
        if rec._match is not None:
            # release through the PINNING core's cache object: even if
            # the source rebuilt, the pinned nodes are host objects the
            # MatchResult still references — release is idempotent and
            # dead-tree releases are harmless
            rec._src_core.release_export(rec._match)

    # ------------------------------------------------------------- state
    @property
    def pending(self) -> int:
        """Live (non-terminal) handoffs — fleet ``has_work`` includes
        them so a staged transfer keeps the step loop running."""
        return len(self.records)

    def snapshot(self) -> List[Dict[str, object]]:
        return [{"fleet_id": r.fleet_id, "src": r.src, "dst": r.dst,
                 "state": r.state, "tokens": r.tokens,
                 "attempts": r.transfer_attempts,
                 "deferred_steps": r.deferred_steps}
                for r in self.records.values()]
