"""Deterministic fault injection for the serving engine's chaos suite.

The engine's recovery paths (deadline unwind, cancellation, degradation
ladder, watchdog quarantine — serving/health.py) are only trustworthy if
they are *driven*, not just written.  This module provides the drive
shaft: named injection points threaded through the engine, the KV pools
and the prefix cache, each firing deterministically on a configured hit
count, behind a hook that is zero-overhead when off (every site guards
with ``if faults is None`` on a plain attribute — no injector object is
even constructed in production).

Injection points (``POINTS``):

  =================  ====================================================
  ``kv_alloc``        ``KVPool.alloc`` raises (admission-time slot
                      claim failure)
  ``block_alloc``     ``BlockPool.alloc`` raises (radix-cache block
                      claim failure)
  ``block_exhausted`` ``PrefixCache._alloc_block`` reports an exhausted
                      pool (graceful-partial-insert path, no raise)
  ``gather``          ``BlockPool.load_row`` raises before dispatching
                      the prefix gather program
  ``scatter``         ``BlockPool.store_row`` raises before dispatching
                      the block scatter program
  ``step``            the engine raises inside the decode region of
                      ``step()`` (watchdog retry/quarantine driver)
  ``nan_logits``      the engine poisons one live slot's KV row with NaN
                      so the *device-side* non-finite detector fires
  ``slow_step``       the engine sleeps ``seconds`` at the top of the
                      step (straggler simulation; deadline driver)
  ``handoff_gather``  the fleet KV handoff raises at its GATHER stage —
                      before the prefill replica's block rows are read
                      (serving/handoff.py; router-level injector)
  ``handoff_scatter`` the handoff raises at its SCATTER stage — after
                      the decode replica's staging slot is claimed,
                      before the blocks land in its pool (proves the
                      temp slot unwinds)
  ``handoff_commit``  the handoff raises at COMMIT — blocks already
                      transferred; the abort path must still release
                      the prefill-side radix pin
  ``replica_spawn``   the fleet autoscaler's spawn path raises while a
                      replica is half-built — it must never become
                      routable and the router topology must be
                      untouched
  ``replica_slow``    ``Router.step`` sleeps ``seconds`` around ONE
                      replica's step (the lowest-index live replica —
                      deterministic), so chaos can straggle a replica
                      at the ROUTER without touching engine internals;
                      the straggler detector must mark it ``slow`` and
                      hedging must cover its at-risk deadline work
  ``hedge_submit``    the router's hedge submission raises before the
                      duplicate lands on the hedge target — the hedge
                      must fail CLOSED (primary attempt untouched, no
                      replica state leaked, accounting conserved)
  ``journal_write``   ``Journal._write`` raises before the record's
                      frame lands — the journal queues the record for
                      retry and the serving loop must not fail the
                      request (serving/journal.py)
  ``journal_fsync``   ``Journal._sync`` raises at the fsync — the bytes
                      stay in the OS cache and the NEXT sync must cover
                      them (fsync is cumulative)
  ``journal_replay``  the recovery scan raises while folding a record —
                      a single fault retries the side-effect-free scan
                      from scratch, a persistent one raises
                      ``JournalError`` with nothing half-recovered
  ``replica_crash``   ``Router.step`` SIGKILLs one live replica
                      (``Router.kill`` — no drain, no close); in-flight
                      work must re-attribute through the existing
                      failover path and the ledger must conserve
  ``aot_load``        the engine's warm-load of ONE program from the
                      AOT store raises before the artifact is read
                      (serving/aot.py; arm on the engine's injector) —
                      the engine must degrade that program to
                      trace-on-demand, never crash
  ``aot_store_corrupt`` ``AOTStore._read_object`` reports the artifact
                      frame corrupt (the CRC-mismatch path a real
                      flipped bit takes; arm on the injector passed to
                      ``AOTStore.open``)
  ``spec_verify``     the engine raises on a SPECULATIVE step, after
                      the draft phase but before the verify dispatch
                      (nothing mutated yet) — the degradation ladder
                      must disable speculation at threshold and the
                      engine keeps serving one token per step, token
                      accounting conserved
  =================  ====================================================

Faults are armed per site with ``enable(site, at=..., times=...)``: the
site's hit counter increments on every pass through the hook, and the
fault fires on hits ``at, at+1, ..., at+times-1`` — the same workload
replayed with the same arming hits the same faults, which is what makes
the chaos suite's token-parity invariant checkable.  ``enable`` /
``disable`` is a registered graftlint ``ResourcePair``: wrap the faulted
window in try/finally so a raising scenario cannot leave a fault armed
for the next test.

``FaultError`` carries ``.site`` so recovery code and tests can assert
*which* injected fault an unwind came from.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["FaultError", "FaultInjector", "POINTS"]

POINTS = ("kv_alloc", "block_alloc", "block_exhausted", "gather",
          "scatter", "step", "nan_logits", "slow_step",
          # fleet-tier sites (ISSUE 13): the disaggregated KV handoff's
          # three stages and the autoscaler's replica spawn — these are
          # checked by ROUTER-level code (serving/handoff.py,
          # serving/autoscaler.py), so arm them on the injector passed
          # to Router/Autoscaler, not on a replica engine's
          "handoff_gather", "handoff_scatter", "handoff_commit",
          "replica_spawn",
          # crash-consistency sites (ISSUE 14): the durable request
          # journal's write/fsync/replay paths (arm on the injector
          # passed to Journal.open) and the router-level simulated
          # replica SIGKILL (arm on the Router's injector)
          "journal_write", "journal_fsync", "journal_replay",
          "replica_crash",
          # tail-latency sites (ISSUE 15): the router-level straggler
          # (sleep around one replica's step — arm on the Router's
          # injector) and the hedge-submission fault (the duplicate
          # submission dies before landing; the hedge fails closed)
          "replica_slow", "hedge_submit",
          # zero-cold-start sites (ISSUE 17): the engine-side warm load
          # of one AOT program (arm on the engine's injector) and the
          # store-side artifact-corruption report (arm on the injector
          # passed to AOTStore.open) — both must degrade the engine to
          # trace-on-demand with accounting and the compile pin intact
          "aot_load", "aot_store_corrupt",
          # speculative decoding (ISSUE 18): the engine-side verify
          # fault — fired on speculative steps before the verify
          # program dispatches, so the ladder's spec_bypass rung is
          # driven with zero device state to unwind
          "spec_verify")


class FaultError(RuntimeError):
    """Raised by an armed injection point (never by production code)."""

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at {site!r} (hit {hit})")
        self.site = site
        self.hit = hit


class _Armed:
    __slots__ = ("at", "times", "seconds", "fired")

    def __init__(self, at: int, times: int, seconds: float):
        self.at = at
        self.times = times
        self.seconds = seconds
        self.fired = 0


class FaultInjector:
    """Per-engine fault plan: arm sites, count hits, fire precisely.

    Pure host state; thread one instance through
    ``ServingEngine(..., faults=...)`` and it reaches the engine, both
    pools and the prefix cache.  All counters survive ``disable`` so a
    test can assert exactly how often each site fired.
    """

    def __init__(self):
        self._armed: Dict[str, _Armed] = {}
        self.hits: Dict[str, int] = {p: 0 for p in POINTS}
        self.fired: Dict[str, int] = {p: 0 for p in POINTS}

    # ------------------------------------------------------------ arming
    def enable(self, site: str, at: int = 0, times: int = 1,
               seconds: float = 0.0) -> None:
        """Arm ``site`` to fire on its next ``times`` hits starting at
        hit index ``at`` (counted from the site's CURRENT hit count, so
        ``at=0`` means "the very next pass").  ``seconds`` parameterises
        ``slow_step``.  Pair every enable with a :meth:`disable` on all
        exit paths (registered graftlint ``ResourcePair``)."""
        if site not in POINTS:
            raise ValueError(
                f"unknown fault site {site!r}; known: {POINTS}")
        if times < 1:
            raise ValueError("times must be >= 1")
        if at < 0:
            raise ValueError("at must be >= 0")
        self._armed[site] = _Armed(self.hits[site] + at, times, seconds)

    def disable(self, site: str) -> None:
        """Disarm ``site`` (idempotent; counters are kept)."""
        self._armed.pop(site, None)

    def disable_all(self) -> None:
        self._armed.clear()

    @property
    def active(self) -> bool:
        return bool(self._armed)

    # ------------------------------------------------------------ firing
    def check(self, site: str) -> Optional[_Armed]:
        """One pass through injection point ``site``: bump its hit
        counter and return the armed record when the fault fires (None
        otherwise).  The *caller* applies the effect — raising, sleeping,
        poisoning — because effects are site-specific."""
        hit = self.hits[site]
        self.hits[site] = hit + 1
        armed = self._armed.get(site)
        if armed is None or not armed.at <= hit < armed.at + armed.times:
            return None
        armed.fired += 1
        self.fired[site] += 1
        return armed

    def fire(self, site: str) -> bool:
        """``check()`` + raise :class:`FaultError` when armed — the
        shape every raising site uses (``kv_alloc``, ``block_alloc``,
        ``gather``, ``scatter``, ``step``).  Returns False when the
        fault did not fire."""
        armed = self.check(site)
        if armed is not None:
            raise FaultError(site, self.hits[site] - 1)
        return False
