"""Durable request journal: the crash-consistency layer of the fleet.

Everything in ``serving/`` so far is fault-tolerant *within* a live
process — quarantine rebuilds, failover, handoff recovery — but a
process crash (OOM kill, host preemption) loses every queued and
in-flight request, because nothing persists.  The :class:`Journal` is
the missing write-ahead log: an append-only, CRC-framed, segment-rotated
record of every request's lifecycle, durable enough that a fresh process
can resume the fleet's promises exactly where the dead one dropped them
(``Router.recover`` — docs/serving.md "Crash recovery").

Record kinds (one JSON payload per CRC frame):

  * ``submit``   — everything needed to re-run the request from zero:
    prompt token ids, ``max_new_tokens``, the full sampling spec
    INCLUDING the seed (the engine's per-slot PRNG discipline makes a
    replayed request token-identical, greedy or sampled), eos token,
    deadlines, and the submit WALL-CLOCK time (``time.time()`` — the
    only clock that survives a process death, so recovery can charge
    downtime against the deadline budget);
  * ``progress`` — the delivered high-water marks of every request that
    advanced this step, batched into ONE record off the step's single
    readback; replay dedups the deterministic regeneration against the
    journaled mark, so a client sees each recorded position at most
    once;
  * ``terminal`` — the request's final status + reason (+ final
    delivered mark).  Exactly one terminal record per submit, across
    process incarnations, is the journal-ledger conservation invariant
    ``fleet_accounting`` enforces.

Framing: every record is ``<u32 payload_len> <u32 crc32(payload)>
<payload>`` appended to the active segment file.  On open the journal
scans all segments in order, folds the replay state, and TRUNCATES a
torn tail (a crash mid-write leaves a half-frame; everything before it
is intact, everything after is garbage by definition — the fuzz test in
tests/test_zz_crash_serving.py truncates at every byte offset and pins
that recovery never raises, never replays a partial record, and never
loses a fully-synced one).  A torn frame in a NON-final segment is real
corruption (sealed segments were fsynced whole) and raises loudly.

Durability semantics (the matrix in docs/serving.md):

  * ``submit`` and ``terminal`` records force an fsync — an accepted
    request is never silently forgotten, a settled one never resurrects;
  * ``progress`` records batch: fsync every ``fsync_batch`` appends (a
    crash may lose the tail of the delivered marks, in which case
    replay re-delivers those positions — token-IDENTICAL by the
    deterministic-regeneration guarantee, so the duplicate is
    idempotent for any client that keys on position);
  * segment rotation (``segment_bytes``) seals the active segment
    (flush + fsync + close) and begins a fresh one —
    ``begin_segment``/``seal_segment`` is a registered graftlint
    ``ResourcePair`` (receiver hint "journal");
  * ``compact()`` deletes sealed segments whose every request is
    terminal — the journal's steady-state size is O(live requests), not
    O(history).

Fault containment: the ``journal_write`` / ``journal_fsync`` injection
points (serving/faults.py) drive the chaos suite.  A failed append is
queued on a pending list and retried on the next append/flush — the
serving loop NEVER fails a request because its journal write did; a
failed fsync leaves the bytes in the OS cache and the next fsync covers
them.  ``journal_replay`` fires during the open scan: a single replay
fault is retried from scratch (the scan has no side effects), a
persistent one raises :class:`JournalError` with nothing half-recovered.

Zero overhead when disabled: every caller guards with ``if journal is
None`` (the same pattern as ``faults``), and the journal itself is pure
host code — no device arrays, no compiled programs, ever.
"""

from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["Journal", "JournalError", "RECORD_KINDS"]

RECORD_KINDS = ("submit", "progress", "terminal")

_HEADER = struct.Struct("<II")          # payload_len, crc32(payload)
# corruption guard: a torn header can decode to any u32 — refuse to
# allocate absurd buffers for a length no sane record reaches
_MAX_PAYLOAD = 64 * 1024 * 1024
_SEGMENT_FMT = "wal-{:08d}.seg"


class JournalError(RuntimeError):
    """Raised on unrecoverable journal state: corruption inside a
    SEALED segment, or a replay that keeps failing after retries."""


class _Ledger:
    """Folded per-request journal state (the replay input AND the
    conservation ledger)."""

    __slots__ = ("submits", "terminals", "delivered", "status", "reason",
                 "record")

    def __init__(self):
        self.submits = 0
        self.terminals = 0
        self.delivered = 0
        self.status: Optional[str] = None
        self.reason: Optional[str] = None
        self.record: Optional[dict] = None   # the submit payload

    @property
    def terminal(self) -> bool:
        return self.terminals > 0


class Journal:
    """Append-only CRC-framed request WAL over a directory of rotated
    segment files (see module docstring).  ``Journal.open`` / ``close``
    is a registered graftlint ``ResourcePair`` — a journal left open on
    an exception path holds an OS file handle and an unflushed tail.

    ``fsync=False`` turns the durability off (unit tests on tmpfs);
    ``faults`` arms the ``journal_*`` chaos points — None in
    production."""

    def __init__(self, path: str, *, segment_bytes: int = 1 << 20,
                 fsync_batch: int = 8, fsync: bool = True,
                 faults=None, replay_retries: int = 1):
        if segment_bytes < 4096:
            raise ValueError("segment_bytes must be >= 4096")
        if fsync_batch < 1:
            raise ValueError("fsync_batch must be >= 1")
        self.path = path
        self.segment_bytes = segment_bytes
        self.fsync_batch = fsync_batch
        self.fsync = fsync
        self.faults = faults
        self.replay_retries = replay_retries
        # plain-int stats (metrics bind lazily via bind_metrics)
        self.records_appended = 0
        self.bytes_appended = 0
        self.fsyncs = 0
        self.write_failures = 0
        self.fsync_failures = 0
        self.segments_sealed = 0
        self.compacted_segments = 0
        self.truncated_bytes = 0
        self.replay_retries_used = 0
        self._metrics = None
        # (frame, record-ids, force-sync) triples whose write raised
        # (journal_write chaos / real IO error): retried before every
        # later append and on flush — the serving loop never loses a
        # record to a transient write fault, and a pended
        # submit/terminal keeps its forced-fsync durability class when
        # it finally lands
        self._pending: List[Tuple[bytes, set, bool]] = []
        self._unsynced = 0
        self._closed = False
        self.state: Dict[int, _Ledger] = {}
        # per-segment id set: a sealed segment is compactable once every
        # request recorded in it is terminal
        self._segment_ids: Dict[str, set] = {}
        os.makedirs(path, exist_ok=True)
        self._segments = sorted(
            f for f in os.listdir(path)
            if f.startswith("wal-") and f.endswith(".seg"))
        self._replay_scan()
        if self._segments:
            active = self._segments[-1]
            self._fh = open(os.path.join(path, active), "ab", buffering=0)
        else:
            self._segments = [_SEGMENT_FMT.format(1)]
            self._segment_ids[self._segments[-1]] = set()
            self._fh = open(os.path.join(path, self._segments[-1]), "ab", buffering=0)

    # ------------------------------------------------------------ open
    @classmethod
    def open(cls, path: str, **kw) -> "Journal":
        """Open (creating if missing) the journal at ``path``: scan all
        segments, fold the replay state, truncate any torn tail, and
        position for append.  Balance with :meth:`close` on every path
        (registered graftlint ``ResourcePair``)."""
        return cls(path, **kw)

    def _replay_scan(self) -> None:
        """Fold every on-disk record into ``self.state``, with the
        ``journal_replay`` chaos point firing per record.  The scan has
        no side effects until it finishes, so a replay fault retries
        from scratch; persistent failure raises with nothing
        half-folded."""
        last_exc: Optional[Exception] = None
        for attempt in range(self.replay_retries + 1):
            if attempt:
                self.replay_retries_used += 1
            try:
                state: Dict[int, _Ledger] = {}
                seg_ids: Dict[str, set] = {}
                for i, seg in enumerate(self._segments):
                    ids = seg_ids.setdefault(seg, set())
                    final = i == len(self._segments) - 1
                    for rec in self._scan_segment(seg, truncate=final):
                        if self.faults is not None:
                            self.faults.fire("journal_replay")
                        self._fold(rec, state, ids)
                self.state = state
                self._segment_ids = seg_ids
                return
            except JournalError:
                raise
            except Exception as e:
                last_exc = e
        raise JournalError(
            f"journal replay failed after {self.replay_retries + 1} "
            f"attempts: {last_exc!r}") from last_exc

    def _scan_segment(self, seg: str, truncate: bool) -> Iterator[dict]:
        """Yield every intact record of one segment file.  A torn tail
        (short header, short payload, or CRC mismatch at the END of the
        file) is truncated away when ``truncate`` (the active segment —
        a crash mid-append is expected); the same damage in a sealed
        segment is corruption and raises."""
        full = os.path.join(self.path, seg)
        if not os.path.exists(full):
            return
        with open(full, "rb") as fh:
            data = fh.read()
        off, n = 0, len(data)
        good = 0
        while off < n:
            if off + _HEADER.size > n:
                break                               # torn header
            length, crc = _HEADER.unpack_from(data, off)
            if length > _MAX_PAYLOAD:
                break                               # garbage length
            end = off + _HEADER.size + length
            if end > n:
                break                               # torn payload
            payload = data[off + _HEADER.size:end]
            if zlib.crc32(payload) != crc:
                break                               # torn/corrupt frame
            try:
                rec = json.loads(payload)
            except ValueError:
                break                               # CRC ok, body not
            off = end
            good = off
            yield rec
        if good < n:
            if not truncate:
                raise JournalError(
                    f"corrupt frame at byte {good} of sealed segment "
                    f"{seg} — sealed segments were fsynced whole; this "
                    f"is real damage, not a torn tail")
            self.truncated_bytes += n - good
            with open(full, "ab", buffering=0) as fh:
                fh.truncate(good)

    @staticmethod
    def _fold(rec: dict, state: Dict[int, _Ledger],
              ids: Optional[set] = None) -> None:
        kind = rec.get("kind")
        if kind == "progress":
            for rid, hwm in rec.get("delivered", {}).items():
                led = state.setdefault(int(rid), _Ledger())
                led.delivered = max(led.delivered, int(hwm))
                if ids is not None:
                    ids.add(int(rid))
            return
        rid = int(rec["id"])
        led = state.setdefault(rid, _Ledger())
        if ids is not None:
            ids.add(rid)
        if kind == "submit":
            led.submits += 1
            led.record = rec
        elif kind == "terminal":
            led.terminals += 1
            led.status = rec.get("status")
            led.reason = rec.get("reason")
            if rec.get("delivered") is not None:
                led.delivered = max(led.delivered, int(rec["delivered"]))

    # ---------------------------------------------------------- append
    def append_submit(self, request_id: int, prompt, max_new_tokens: int,
                      sampling: Optional[dict] = None,
                      eos_token_id: Optional[int] = None,
                      deadline_s: Optional[float] = None,
                      ttft_deadline_s: Optional[float] = None,
                      wall_time: Optional[float] = None,
                      priority: str = "interactive") -> None:
        """Journal one accepted submission (forces a sync: an accepted
        request must survive the very next crash).  ``sampling`` is the
        plain-dict sampling spec INCLUDING the seed; ``wall_time``
        defaults to ``time.time()`` — the downtime clock recovery
        charges deadlines against."""
        self._append({
            "kind": "submit", "id": int(request_id),
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
            "sampling": sampling,
            "eos_token_id": None if eos_token_id is None
            else int(eos_token_id),
            "deadline_s": None if deadline_s is None
            else float(deadline_s),
            "ttft_deadline_s": None if ttft_deadline_s is None
            else float(ttft_deadline_s),
            "wall_time": time.time() if wall_time is None
            else float(wall_time),
            # priority class survives the crash so a recovered batch
            # request is still sheddable (old journals lack the key —
            # readers default it to "interactive")
            "priority": str(priority),
        }, sync=True)

    def append_progress(self, delivered: Dict[int, int]) -> None:
        """Journal this step's delivered high-water marks — ONE record
        for the whole batch, synced only at the ``fsync_batch``
        cadence."""
        if not delivered:
            return
        self._append({"kind": "progress",
                      "delivered": {str(k): int(v)
                                    for k, v in delivered.items()}},
                     sync=False)

    def append_terminal(self, request_id: int, status: str, reason: str,
                        delivered: Optional[int] = None) -> None:
        """Journal one terminal disposition (forces a sync: a settled
        request must never be replayed by the next incarnation)."""
        self._append({"kind": "terminal", "id": int(request_id),
                      "status": status, "reason": str(reason)[:500],
                      "delivered": delivered}, sync=True)

    def _append(self, rec: dict, sync: bool) -> None:
        if self._closed:
            raise JournalError("journal is closed")
        payload = json.dumps(rec, separators=(",", ":")).encode()
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        # the folded state advances even when the physical write defers
        # to the pending queue — the bytes WILL land (retried every
        # later append/flush), and the live process must see its own
        # writes immediately.  Segment attribution happens inside
        # _write, AFTER any rotation, so compact() can never delete a
        # sealed segment that physically holds a live record.
        rec_ids: set = set()
        self._fold(rec, self.state, rec_ids)
        # a retried submit/terminal frame that lands NOW still owes its
        # forced fsync — durability class travels with the frame
        force = self._retry_pending()
        try:
            self._write(frame, rec_ids)
        except Exception:
            self.write_failures += 1
            if self._metrics is not None:
                self._metrics["write_failures"].inc()
            self._pending.append((frame, rec_ids, sync))
            if force:
                self._sync()
            return
        self._unsynced += 1
        if sync or force or self._unsynced >= self.fsync_batch:
            self._sync()

    def _write(self, frame: bytes, rec_ids: set) -> None:
        if self.faults is not None:
            self.faults.fire("journal_write")
        if self._fh.tell() + len(frame) > self.segment_bytes \
                and self._fh.tell() > 0:
            self.seal_segment()
            self.begin_segment()
        self._fh.write(frame)
        # attributed to the segment the frame actually LANDED in —
        # rotation above may have changed the active segment
        self._segment_ids[self._segments[-1]].update(rec_ids)
        self.records_appended += 1
        self.bytes_appended += len(frame)
        if self._metrics is not None:
            self._metrics["records"].inc()
            self._metrics["bytes"].inc(len(frame))

    def _retry_pending(self) -> bool:
        """Drain the pending-write queue; returns True when any landed
        frame carried the forced-fsync class (the caller must sync)."""
        force = False
        while self._pending:
            frame, rec_ids, sync = self._pending[0]
            try:
                self._write(frame, rec_ids)
            except Exception:
                return force            # still failing; keep the queue
            self._pending.pop(0)
            self._unsynced += 1
            force |= sync
        return force

    def _sync(self) -> None:
        """Flush python buffers and fsync the active segment.  A failed
        fsync is counted and retried implicitly: the bytes stay in the
        OS cache and the NEXT sync covers them (fsync is cumulative)."""
        try:
            self._fh.flush()
            if self.faults is not None:
                self.faults.fire("journal_fsync")
            if self.fsync:
                os.fsync(self._fh.fileno())
            self.fsyncs += 1
            self._unsynced = 0
            if self._metrics is not None:
                self._metrics["fsyncs"].inc()
        except Exception:
            self.fsync_failures += 1
            if self._metrics is not None:
                self._metrics["fsync_failures"].inc()

    def flush(self) -> None:
        """Drain the pending-write queue and fsync whatever is
        buffered (no-op on a closed/crashed journal — there is nothing
        left to make durable)."""
        if self._closed:
            return
        self._retry_pending()
        if self._unsynced or self._pending:
            self._sync()

    # -------------------------------------------------------- segments
    @property
    def segments(self) -> Tuple[str, ...]:
        return tuple(self._segments)

    def begin_segment(self) -> str:
        """Open a fresh active segment (the rotation's second half).
        Balance with :meth:`seal_segment` — registered graftlint
        ``ResourcePair`` (a begun segment left unsealed at rotation
        time would interleave two active tails)."""
        seq = int(self._segments[-1][4:-4]) + 1 if self._segments else 1
        name = _SEGMENT_FMT.format(seq)
        self._segments.append(name)
        self._segment_ids[name] = set()
        self._fh = open(os.path.join(self.path, name), "ab", buffering=0)
        if self._metrics is not None:
            self._metrics["segments"].set(len(self._segments))
        return name

    def seal_segment(self) -> None:
        """Close the active segment durably (flush + fsync + close):
        sealed segments are immutable — a torn frame found in one later
        is corruption, not a crash artifact."""
        try:
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self.fsyncs += 1
        except Exception:
            self.fsync_failures += 1
        self._fh.close()
        self._unsynced = 0
        self.segments_sealed += 1

    def compact(self) -> int:
        """Delete every SEALED segment whose recorded requests are all
        terminal — replay would skip every one of their records, so the
        bytes are dead weight.  Returns the number of segments
        removed."""
        removed = 0
        for seg in self._segments[:-1]:         # never the active one
            ids = self._segment_ids.get(seg, set())
            if all(self.state.get(i) is not None
                   and self.state[i].terminal for i in ids):
                try:
                    os.unlink(os.path.join(self.path, seg))
                except FileNotFoundError:
                    pass
                self._segments.remove(seg)
                self._segment_ids.pop(seg, None)
                removed += 1
        self.compacted_segments += removed
        if self._metrics is not None and removed:
            self._metrics["compacted"].inc(removed)
            self._metrics["segments"].set(len(self._segments))
        return removed

    # --------------------------------------------------------- reading
    def records(self) -> Iterator[dict]:
        """Re-read every record from disk in order (a FRESH scan — the
        audit view, not the folded state)."""
        for i, seg in enumerate(self._segments):
            yield from self._scan_segment(
                seg, truncate=i == len(self._segments) - 1)

    def replay(self) -> Dict[int, dict]:
        """The recovery input: every NON-terminal submit's journaled
        view — ``{id: {"record": <submit payload>, "delivered": hwm}}``
        (requests with a terminal record are done; progress-only ids —
        their submit record failed to land — cannot be replayed and are
        skipped)."""
        out: Dict[int, dict] = {}
        for rid, led in self.state.items():
            if led.terminal or led.record is None:
                continue
            out[rid] = {"record": dict(led.record),
                        "delivered": led.delivered}
        return out

    def ledger(self) -> Dict[int, Dict[str, object]]:
        """The conservation ledger ``fleet_accounting`` audits:
        per-request submit/terminal record counts, the delivered
        high-water mark, and the terminal status."""
        return {rid: {"submits": led.submits,
                      "terminals": led.terminals,
                      "delivered": led.delivered,
                      "status": led.status}
                for rid, led in self.state.items()}

    def position(self) -> Dict[str, object]:
        """Where the journal is — the stall/crash diagnostic
        (``Router.stall_snapshot`` embeds it)."""
        return {
            "path": self.path,
            "segment": self._segments[-1] if self._segments else None,
            "segments": len(self._segments),
            "records": self.records_appended,
            "pending_writes": len(self._pending),
            "unsynced": self._unsynced,
            "write_failures": self.write_failures,
            "fsync_failures": self.fsync_failures,
            "live_requests": sum(1 for led in self.state.values()
                                 if not led.terminal),
        }

    # ------------------------------------------------------- lifecycle
    def bind_metrics(self, registry) -> None:
        """Bind the ``journal.*`` instruments into an
        ``obs.MetricsRegistry`` (get-or-create — a shared fleet registry
        aggregates; glossary rows in docs/observability.md)."""
        c, g = registry.counter, registry.gauge
        self._metrics = {
            "records": c("journal.records",
                         "journal records appended (all kinds)"),
            "bytes": c("journal.bytes", "journal bytes appended"),
            "fsyncs": c("journal.fsyncs", "journal fsync calls issued"),
            "write_failures": c("journal.write_failures",
                                "journal appends that failed and were "
                                "queued for retry"),
            "fsync_failures": c("journal.fsync_failures",
                                "journal fsyncs that failed (bytes stay "
                                "in OS cache; next sync covers them)"),
            "compacted": c("journal.compacted_segments",
                           "fully-terminal sealed segments deleted"),
            "segments": g("journal.segments",
                          "journal segment files currently on disk"),
        }
        self._metrics["segments"].set(len(self._segments))

    def crash(self) -> None:
        """Chaos/test helper: die WITHOUT flushing — pending and
        buffered-but-unsynced writes are dropped on the floor exactly as
        a SIGKILL would drop them.  The on-disk state is whatever the
        durability matrix already guaranteed.  After this the journal
        object is closed; reopen the path to recover."""
        self._pending.clear()
        self._closed = True
        try:
            self._fh.close()
        except Exception:
            pass

    def close(self) -> None:
        """Flush + fsync + close (idempotent).  The graceful half of the
        open/close ``ResourcePair``."""
        if self._closed:
            return
        self.flush()
        self._closed = True
        self._fh.close()
