"""Step-level health state machine, degradation ladder, circuit breaker.

Pure host-side control plane consumed by ``engine.EngineCore``'s
watchdog (enabled via ``ServingEngine(fault_tolerance=...)``):

  * :class:`FaultToleranceConfig` — the knobs: step-retry budget and
    exponential backoff, per-subsystem fault threshold before the
    degradation ladder disables it, quarantine limit/window for the
    circuit breaker, and the bounded submit queue;
  * :class:`DegradationLadder` — per-OPTIONAL-subsystem fault counters
    (``prefix_cache``, ``chunked_prefill``, ``fused_decode``,
    ``spec_verify``): a subsystem that faults ``ladder_threshold``
    times is disabled and the engine keeps serving without it (cache →
    bypass, chunking → whole-bucket, fused decode → composed path,
    speculation → one token per step);
  * :class:`EngineHealth` — the state machine
    ``healthy → degraded → quarantined`` (+ terminal ``circuit_open``):
    consecutive core-step faults earn exponential-backoff retries until
    the budget is spent, then the engine quarantines (fails the
    implicated in-flight requests, rebuilds the compiled program set and
    pools, re-queues unstarted work).  ``enter_quarantine`` /
    ``leave_quarantine`` is a registered graftlint ``ResourcePair`` —
    rebuilds must close the window on every path.  The circuit breaker
    stops flapping: ``circuit_quarantine_limit`` quarantines within
    ``circuit_window_steps`` engine steps open the circuit, and the
    engine fails fast instead of rebuilding forever.

State codes for the ``serving.health_state`` gauge (docs/observability.md
glossary): 0 healthy, 1 degraded, 2 quarantined, 3 circuit_open.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Optional, Tuple

__all__ = ["FaultToleranceConfig", "DegradationLadder", "EngineHealth",
           "HEALTHY", "DEGRADED", "QUARANTINED", "CIRCUIT_OPEN",
           "STATE_CODES", "SUBSYSTEMS"]

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
CIRCUIT_OPEN = "circuit_open"
STATE_CODES = {HEALTHY: 0, DEGRADED: 1, QUARANTINED: 2, CIRCUIT_OPEN: 3}

# the optional subsystems the ladder may disable, in ladder order — the
# engine serves correctly (if slower) without any of them
SUBSYSTEMS: Tuple[str, ...] = ("prefix_cache", "chunked_prefill",
                               "fused_decode", "spec_verify")


@dataclasses.dataclass
class FaultToleranceConfig:
    """Watchdog/backpressure knobs (see docs/serving.md for the
    recovery matrix these parameterise)."""
    max_step_retries: int = 3       # consecutive core-step faults before
                                    # quarantine
    backoff_base_s: float = 0.02    # sleep 2^(n-1) * base after fault n
    backoff_cap_s: float = 1.0
    ladder_threshold: int = 2       # faults per optional subsystem
                                    # before it is disabled
    circuit_quarantine_limit: int = 3
    circuit_window_steps: int = 512  # quarantines counted within this
                                     # many engine steps trip the breaker
    max_queue: Optional[int] = None  # bounded submit queue (None = off)

    def __post_init__(self):
        if self.max_step_retries < 1:
            raise ValueError("max_step_retries must be >= 1")
        if self.backoff_base_s < 0 or self.backoff_cap_s < 0:
            raise ValueError("backoff seconds must be >= 0")
        if self.ladder_threshold < 1:
            raise ValueError("ladder_threshold must be >= 1")
        if self.circuit_quarantine_limit < 1:
            raise ValueError("circuit_quarantine_limit must be >= 1")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")


class DegradationLadder:
    """Fault counters per optional subsystem; disabling is monotone for
    the engine's lifetime (a quarantine rebuild resets device state, not
    the operator-visible decision that a subsystem is unreliable)."""

    def __init__(self, threshold: int):
        self.threshold = threshold
        self._faults = {s: 0 for s in SUBSYSTEMS}
        self._disabled = {s: False for s in SUBSYSTEMS}

    def record_fault(self, subsystem: str) -> bool:
        """Count one fault; returns True exactly once — when the count
        crosses the threshold and the subsystem should now be disabled."""
        if subsystem not in self._faults:
            raise ValueError(f"unknown subsystem {subsystem!r}")
        if self._disabled[subsystem]:
            return False
        self._faults[subsystem] += 1
        if self._faults[subsystem] >= self.threshold:
            self._disabled[subsystem] = True
            return True
        return False

    def disabled(self, subsystem: str) -> bool:
        return self._disabled[subsystem]

    @property
    def level(self) -> int:
        """Number of disabled subsystems — the ``serving.
        degradation_level`` gauge value (0 = full service)."""
        return sum(1 for v in self._disabled.values() if v)

    @property
    def disabled_subsystems(self) -> Tuple[str, ...]:
        return tuple(s for s in SUBSYSTEMS if self._disabled[s])


class _QuarantineToken:
    """Handle returned by ``enter_quarantine`` and consumed by
    ``leave_quarantine`` — the pair the lifecycle lint rule tracks."""

    __slots__ = ("reason", "t0")

    def __init__(self, reason: str, t0: float):
        self.reason = reason
        self.t0 = t0


class EngineHealth:
    """The watchdog's bookkeeping: consecutive-fault counter, retry
    backoff schedule, quarantine history, breaker state.  The ENGINE
    performs the actual unwind/rebuild; this class only decides."""

    def __init__(self, cfg: FaultToleranceConfig):
        self.cfg = cfg
        self.consecutive_faults = 0
        self.quarantine_count = 0
        self.step_index = 0              # engine steps seen (ok or not)
        self._quarantine_steps: Deque[int] = deque(
            maxlen=cfg.circuit_quarantine_limit)
        self._in_quarantine = False
        self._circuit_open = False
        self.degraded = False            # set by the engine (ladder > 0)
        # the STRAGGLER signal (docs/serving.md "Tail latency"): set by
        # a fleet router's outlier detector when this replica's step
        # latency is a fleet-relative outlier, cleared with hysteresis
        # when it recovers.  Slow is an overlay on the state machine,
        # not a state: a slow replica stays routable (correct, just
        # late) and is DEPRIORITIZED by the route order — between
        # healthy and degraded — rather than excluded.
        self.slow = False
        self.slow_reason: Optional[str] = None
        self.last_fault: Optional[str] = None

    # ------------------------------------------------------------- state
    @property
    def state(self) -> str:
        if self._circuit_open:
            return CIRCUIT_OPEN
        if self._in_quarantine:
            return QUARANTINED
        if self.degraded or self.consecutive_faults > 0:
            return DEGRADED
        return HEALTHY

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.state]

    @property
    def circuit_open(self) -> bool:
        return self._circuit_open

    @property
    def routable(self) -> bool:
        """May a fleet router hand this engine NEW work?  Quarantined
        (mid-rebuild) and circuit-open (terminal) replicas may not;
        degraded replicas stay routable — the router deprioritizes
        rather than excludes them (docs/serving.md health matrix)."""
        return not (self._in_quarantine or self._circuit_open)

    # ------------------------------------------------------------- steps
    def on_step_ok(self) -> None:
        self.step_index += 1
        self.consecutive_faults = 0

    def record_step_fault(self, reason: str) -> Optional[float]:
        """One core-step fault.  Returns the backoff to sleep before the
        next retry, or None when the retry budget is spent and the
        caller must quarantine."""
        self.step_index += 1
        self.last_fault = reason
        self.consecutive_faults += 1
        n = self.consecutive_faults
        if n > self.cfg.max_step_retries:
            return None
        return min(self.cfg.backoff_base_s * (2 ** (n - 1)),
                   self.cfg.backoff_cap_s)

    def mark_slow(self, reason: str) -> None:
        """Stamp the straggler signal (a fleet router's outlier
        detector owns the decision; this just records it)."""
        self.slow = True
        self.slow_reason = reason

    def clear_slow(self) -> None:
        """The straggler recovered (hysteresis already applied by the
        detector)."""
        self.slow = False
        self.slow_reason = None

    def mark_dead(self, reason: str) -> None:
        """Pin this engine terminally dead — the state a fleet router
        stamps on a KILLED replica (``Router.kill``'s simulated
        SIGKILL).  Implemented as an opened circuit: ``routable`` goes
        False forever, ``submit`` fail-fasts with ``circuit_open``, and
        ``step`` becomes a no-op — so a stale direct reference to the
        dead engine can never serve a request the fleet believes is
        owned elsewhere."""
        self._circuit_open = True
        self.last_fault = reason

    # -------------------------------------------------------- quarantine
    def enter_quarantine(self, reason: str) -> _QuarantineToken:
        """Open a quarantine window (rebuild in progress).  Balance with
        :meth:`leave_quarantine` in a finally block — registered
        graftlint ``ResourcePair``."""
        self._in_quarantine = True
        self.quarantine_count += 1
        self._quarantine_steps.append(self.step_index)
        q = self._quarantine_steps
        if len(q) >= self.cfg.circuit_quarantine_limit \
                and q[-1] - q[0] <= self.cfg.circuit_window_steps:
            self._circuit_open = True
        return _QuarantineToken(reason, time.perf_counter())

    def leave_quarantine(self, token: _QuarantineToken) -> float:
        """Close the window; returns its duration in seconds."""
        self._in_quarantine = False
        self.consecutive_faults = 0
        return time.perf_counter() - token.t0
