"""Serving robustness exceptions: loud, typed, diagnosis-carrying.

The fault-tolerance contract (docs/serving.md "Fault tolerance") is that
no request ever ends ambiguously and no failure mode spins silently —
these exception types are the loud half of that contract.  Validation
errors at ``submit()`` stay plain ``ValueError``s (caller bugs);
capacity/SLO rejections raise :class:`RequestRejected` (healthy-system
backpressure, carrying the retry hint); a wedged step loop raises
:class:`EngineStalledError` (engine bug or unrecoverable fault, carrying
the diagnostic snapshot).
"""

from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["RequestRejected", "EngineStalledError"]


class RequestRejected(RuntimeError):
    """``submit()`` refused the request — backpressure, not failure.

    ``reason`` is one of ``"queue_full"`` (the bounded submit queue is at
    ``max_queue``), ``"slo_unattainable"`` (projected TTFT already
    exceeds the request's ``ttft_deadline_s`` at submit time), or
    ``"circuit_open"`` (the engine's recovery circuit breaker tripped).
    The fleet router (serving/router.py) adds four fleet-scoped
    reasons: ``"fleet_queue_full"`` (the router-level bounded queue
    across all replicas), ``"no_healthy_replica"`` (every replica
    excluded by health state or drain), and the brownout ladder's
    ``"brownout_shed_batch"`` / ``"brownout_overload"`` (docs/serving.md
    "Tail latency").
    ``retry_after_s`` is the live-metrics-derived hint, always finite
    and clamped (``serving.metrics.MAX_RETRY_AFTER_S``; None when the
    engine has no throughput history yet, or will never recover —
    circuit_open).  ``output`` is the terminal
    :class:`~paddle_tpu.serving.api.RequestOutput` view with
    ``status="rejected"`` so callers that log every request still see an
    unambiguous terminal record.

    ``per_replica`` (fleet rejections where every eligible replica
    refused) carries EVERY replica's own rejection — a list of
    ``{"replica", "reason", "retry_after_s"}`` dicts in try order — so
    a heterogeneous refusal (one replica queue-full, another
    SLO-hopeless) is debuggable from the exception alone; the
    ``output.status_reason`` embeds the same breakdown in its text.
    """

    def __init__(self, reason: str, retry_after_s: Optional[float] = None,
                 output=None,
                 per_replica: Optional[List[Dict[str, object]]] = None):
        hint = "" if retry_after_s is None \
            else f" (retry after ~{retry_after_s:.3f}s)"
        super().__init__(f"request rejected: {reason}{hint}")
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.output = output
        self.per_replica = per_replica


class EngineStalledError(RuntimeError):
    """``run_until_complete`` detected a no-progress stall: N consecutive
    steps emitted no token, admitted no request and ran no prefill chunk
    while work was still queued.  Carries a host-state snapshot (queue
    depth, free slots/blocks, per-slot positions, health state) so the
    wedge is diagnosable from the exception alone instead of from a
    spinning process."""

    def __init__(self, stall_steps: int, snapshot: Dict[str, object]):
        lines = ", ".join(f"{k}={v}" for k, v in snapshot.items())
        super().__init__(
            f"engine made no progress for {stall_steps} consecutive "
            f"steps with work queued — {lines}")
        self.stall_steps = stall_steps
        self.snapshot = dict(snapshot)
