"""Public serving surface: ``ServingEngine.submit()/step()/stream()``.

The facade over ``engine.EngineCore``: request construction, streaming
token callbacks, the synchronous ``serve_batch()`` convenience, and the
metrics dict.  Typical use::

    from paddle_tpu.serving import ServingEngine, SamplingParams

    eng = ServingEngine(model, num_slots=8)
    h = eng.submit([12, 7, 99], max_new_tokens=32,
                   sampling=SamplingParams(do_sample=True, top_p=0.9),
                   eos_token_id=0)
    for tok in eng.stream(h):          # steps the engine as it yields
        ...
    out = eng.result(h)                # RequestOutput

or, batch-synchronous::

    outs = eng.serve_batch(prompts, max_new_tokens=32)  # list per prompt
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from .engine import EngineCore
from .errors import RequestRejected
from .health import FaultToleranceConfig
from .metrics import ServingMetrics
from .scheduler import PRIORITIES, Request, SamplingParams

__all__ = ["ServingEngine", "RequestOutput", "Request", "SamplingParams"]


@dataclasses.dataclass
class RequestOutput:
    """Completed (or in-flight) view of one request."""
    request_id: int
    prompt: np.ndarray
    tokens: List[int]
    finished: bool
    finish_reason: Optional[str]      # "eos" | "length" | None
    ttft_s: Optional[float]           # submit -> first token
    prefix_hit_tokens: int = 0        # prompt tokens served from cache
    # terminal disposition (docs/serving.md "Fault tolerance"): exactly
    # one of "finished" | "cancelled" | "deadline_exceeded" |
    # "rejected" | "failed" once the request is done (None in flight);
    # status_reason carries the why ("eos", "TTFT deadline ...", the
    # fault repr, ...) so no request ever ends ambiguously
    status: Optional[str] = None
    status_reason: Optional[str] = None

    @property
    def sequence(self) -> np.ndarray:
        """prompt + generated tokens, the ``generate()``-shaped result."""
        return np.concatenate(
            [np.asarray(self.prompt, np.int64),
             np.asarray(self.tokens, np.int64)])


class ServingEngine:
    """Continuous-batching serving over any causal LM exposing
    ``init_cache``/``decode_step`` (GPTForCausalLM, LlamaForCausalLM).

    ``num_slots`` fixes the decode batch; ``max_seq`` the per-slot KV
    budget (default: the model's max_seq_len).  All shapes are static:
    admission cost is bounded by the pow2 prefill buckets, decode is one
    compiled program for the engine's lifetime.

    Prefix reuse (``enable_prefix_cache``, default on): prompts sharing a
    block-aligned prefix with earlier traffic skip its recompute — the
    radix cache (serving/prefix_cache.py) copies the cached KV blocks
    into the slot and only the suffix prefills, so TTFT is O(suffix).
    ``prefill_chunk`` additionally splits long suffixes into fixed-width
    chunks interleaved with decode (one chunk per step), bounding the
    decode stall an 8k admission can inject.
    ``max_prefill_tokens_per_step`` caps admission prefill work per step;
    when the queue head exceeds it a later small request may be admitted
    first (bounded skip — see ``Scheduler``).
    """

    def __init__(self, model, num_slots: int = 8,
                 max_seq: Optional[int] = None, min_bucket: int = 16,
                 max_prefills_per_step: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_prefill_tokens_per_step: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 block_len: int = 16,
                 prefix_blocks: Optional[int] = None,
                 record_events: bool = False,
                 registry=None, tracer=None,
                 fused_decode: bool = False,
                 fault_tolerance: Optional[FaultToleranceConfig] = None,
                 faults=None,
                 max_queue: Optional[int] = None,
                 tensor_parallel: int = 1,
                 collective_fusion: bool = True,
                 role: str = "unified",
                 journal=None,
                 aot_store=None,
                 spec_k: int = 0):
        # fleet role metadata (docs/serving.md "Disaggregated fleet"):
        # "prefill" replicas take only the router's prefill-stage work
        # (large prefill buckets, few slots), "decode" replicas take
        # decode-stage work (all slots), "unified" takes both.  The
        # engine itself behaves identically — the role is the routing
        # contract the fleet Router reads when its ``roles=`` is omitted
        if role not in ("prefill", "decode", "unified"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'unified', "
                f"got {role!r}")
        self.role = role
        # registry/tracer (paddle_tpu.obs) may be shared across engines
        # (a fleet scraping one Prometheus surface: shared instruments
        # aggregate, lanes come from per-engine blocks); default: private
        # tensor_parallel > 1 shards the engine over a 1-D mesh (model
        # weights, KV slabs, every compiled program); collective_fusion
        # opts the decode step into the fused compute-collective
        # shard_map path — see docs/serving.md "Tensor-parallel serving"
        self.core = EngineCore(
            model, num_slots=num_slots, max_seq=max_seq,
            min_bucket=min_bucket,
            max_prefills_per_step=max_prefills_per_step,
            prefill_chunk=prefill_chunk,
            max_prefill_tokens_per_step=max_prefill_tokens_per_step,
            enable_prefix_cache=enable_prefix_cache,
            block_len=block_len, prefix_blocks=prefix_blocks,
            metrics=ServingMetrics(record_events=record_events,
                                   registry=registry, tracer=tracer),
            fused_decode=fused_decode,
            fault_tolerance=fault_tolerance, faults=faults,
            max_queue=max_queue,
            tensor_parallel=tensor_parallel,
            collective_fusion=collective_fusion,
            # durable request journal (serving/journal.py): single-
            # engine deployments journal with ENGINE request ids; a
            # fleet journals at the Router with fleet ids instead, so
            # replicas behind a Router are built journal-less
            journal=journal,
            # zero-cold-start (docs/serving.md "Zero cold start"): an
            # attached AOT program store makes construction a LOAD —
            # the engine installs pre-lowered artifacts instead of
            # tracing, falling back per program on any miss/skew
            aot_store=aot_store,
            # speculative decoding (docs/serving.md "Speculative
            # decoding"): spec_k > 0 adds ONE batched verify program —
            # per-slot n-gram drafts checked in a single fixed-shape
            # [num_slots, spec_k+1] dispatch; token streams are
            # identical to spec_k=0, only faster
            spec_k=spec_k)
        if journal is not None:
            journal.bind_metrics(self.core.metrics.registry)
            if journal.state:
                # a reopened journal already holds request ids — the
                # engine's counter must start past them or the new
                # run's records alias the dead run's in the ledger
                # (the Router does the same for fleet ids)
                self.core.scheduler.start_ids(max(journal.state) + 1)
        self._requests = {}

    # -------------------------------------------------------- submission
    def submit(self, prompt, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               eos_token_id: Optional[int] = None,
               stream: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None,
               priority: str = "interactive",
               allowed_tokens: Optional[Sequence[int]] = None) -> int:
        """Queue one request; returns its id (admission happens inside a
        later ``step()`` — submit never blocks on the device).

        ``stream`` is called as ``stream(request, token)`` the moment
        each token is harvested, while other requests keep decoding.

        Everything knowable at submit time is validated HERE, before the
        request enters the system (``ValueError`` — caller bug), and
        backpressure is applied here too (:class:`RequestRejected` with
        a retry-after hint — healthy-system flow control): bounded queue
        (``max_queue``), SLO-aware rejection when the projected TTFT
        already exceeds ``ttft_deadline_s``, circuit-open fail-fast.
        ``deadline_s``/``ttft_deadline_s`` are seconds relative to this
        call, checked host-side every step; a blown deadline unwinds the
        request with terminal status ``deadline_exceeded``.

        ``priority`` is the request's class (``"interactive"`` —
        latency-sensitive, the default — or ``"batch"`` — deferrable
        offline work): admission prefers interactive inside the bounded
        skip window, and a fleet router's brownout sheds batch first
        under sustained overload (docs/serving.md "Tail latency").

        ``allowed_tokens`` constrains decoding to a token set: the
        engine applies it as a per-slot vocab mask INSIDE the existing
        decode/verify programs (a traced operand — zero new compiled
        programs), so sampling can never emit an out-of-set token.
        Speculation composes: drafts are truncated at the first
        out-of-set token, so a constrained slot still speculates within
        its set (docs/serving.md "Constrained decoding")."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError(
                "prompt is empty (no tokens survive int32 flattening) — "
                "at least one token is required")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        max_seq = self.core.pool.max_seq
        if prompt.size + max_new_tokens > max_seq:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{max_new_tokens} exceeds the pool max_seq {max_seq} — "
                f"the request could never be placed; truncate the "
                f"prompt or lower max_new_tokens")
        for name, d in (("deadline_s", deadline_s),
                        ("ttft_deadline_s", ttft_deadline_s)):
            if d is not None and d < 0:
                raise ValueError(f"{name} must be >= 0, got {d}")
        sampling = sampling or SamplingParams()
        sampling.validate()
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        if allowed_tokens is not None:
            allowed_tokens = np.unique(
                np.asarray(allowed_tokens, np.int64).reshape(-1))
            if allowed_tokens.size < 1:
                raise ValueError(
                    "allowed_tokens is empty — an unsatisfiable "
                    "constraint can never emit a token; pass None for "
                    "unconstrained decoding")
            vocab = int(self.core.model.cfg.vocab_size)
            lo, hi = int(allowed_tokens[0]), int(allowed_tokens[-1])
            if lo < 0 or hi >= vocab:
                raise ValueError(
                    f"allowed_tokens must lie in [0, {vocab}) — got "
                    f"range [{lo}, {hi}]")
        sched = self.core.scheduler
        req = Request(request_id=sched.next_request_id(),
                      prompt=prompt, max_new_tokens=max_new_tokens,
                      sampling=sampling,
                      eos_token_id=eos_token_id, stream=stream,
                      priority=priority,
                      deadline_s=deadline_s,
                      ttft_deadline_s=ttft_deadline_s,
                      allowed_tokens=allowed_tokens)
        try:
            self.core.check_admission(req)
        except RequestRejected as e:
            e.output = RequestOutput(
                request_id=req.request_id, prompt=req.prompt, tokens=[],
                finished=True, finish_reason=None, ttft_s=None,
                status="rejected", status_reason=e.reason)
            raise
        sched.submit(req)
        self._requests[req.request_id] = req
        self.core.metrics.on_submit()
        if self.core.journal is not None:
            # journaled ONLY after acceptance: a rejected submission
            # raised above and owes the ledger nothing
            self.core.journal.append_submit(
                req.request_id, req.prompt, max_new_tokens,
                sampling=dataclasses.asdict(sampling),
                eos_token_id=eos_token_id, deadline_s=deadline_s,
                ttft_deadline_s=ttft_deadline_s, priority=priority)
        return req.request_id

    def cancel(self, request_id: int) -> RequestOutput:
        """Cleanly unwind one request in any state — queued, mid-
        (chunked-)prefill, or decoding — freeing its pool slot, staging
        rows and pinned radix path immediately; returns the terminal
        view (status ``cancelled``, or the earlier terminal status if
        the request had already ended: cancellation is idempotent)."""
        req = self._requests.get(request_id)
        if req is None:
            raise KeyError(
                f"unknown request_id {request_id} — never submitted to "
                f"this engine, or already purged")
        if not req.finished:
            self.core.cancel(request_id)
        return self.result(request_id)

    # -------------------------------------------------------- execution
    def step(self) -> int:
        """One engine iteration (admit -> decode -> harvest/evict);
        returns the number of requests still in flight."""
        return self.core.step()

    def stream(self, request_id: int) -> Iterator[int]:
        """Yield ``request_id``'s tokens as they are generated, stepping
        the engine whenever the request has no unseen tokens yet.  Other
        in-flight requests advance on the same steps."""
        req = self._requests[request_id]
        seen = 0
        while True:
            while seen < len(req.tokens):
                yield req.tokens[seen]
                seen += 1
            if req.finished:
                return
            self.core.step()

    def run_until_complete(self, max_steps: Optional[int] = None,
                           stall_steps: Optional[int] = 64) -> int:
        return self.core.run_until_complete(max_steps,
                                            stall_steps=stall_steps)

    # ----------------------------------------------------------- results
    def result(self, request_id: int) -> RequestOutput:
        req = self._requests[request_id]
        ttft = None
        if req.first_token_time is not None:
            ttft = req.first_token_time - req.arrival_time
        return RequestOutput(request_id=req.request_id, prompt=req.prompt,
                             tokens=list(req.tokens), finished=req.finished,
                             finish_reason=req.finish_reason, ttft_s=ttft,
                             prefix_hit_tokens=req.prefix_hit_tokens,
                             status=req.status,
                             status_reason=req.status_reason)

    def purge(self, request_id: int) -> RequestOutput:
        """``result()`` + drop the engine's reference to the request.
        Long-running servers MUST consume results this way (or call it
        after ``result()``): the engine otherwise keeps every
        prompt/token list for its whole lifetime.  Purging a request
        that is STILL IN FLIGHT cancels it first (queued, mid-chunked-
        prefill, or decoding — slot, staging rows and radix pin are all
        returned), so an abandoning client always leaves the engine
        clean."""
        req = self._requests[request_id]
        if not req.finished:
            self.core.cancel(request_id,
                             reason="purged while in flight")
        out = self.result(request_id)
        del self._requests[request_id]
        return out

    def serve_batch(self, prompts: Sequence, max_new_tokens: int = 16,
                    sampling: Optional[SamplingParams] = None,
                    eos_token_id: Optional[int] = None,
                    max_steps: Optional[int] = None) -> List[RequestOutput]:
        """Submit every prompt, run to completion, return outputs in
        submission order — the synchronous convenience for offline batch
        inference (ragged prompts welcome; no padding needed).  A shared
        ``sampling`` spec is copied per request with the seed offset by
        the prompt index, so equal prompts still decode independently.
        The returned outputs are PURGED from the engine (they carry the
        full result) — batch after batch never accumulates state."""
        ids = [self.submit(p, max_new_tokens=max_new_tokens,
                           sampling=dataclasses.replace(
                               sampling, seed=sampling.seed + i)
                           if sampling is not None else None,
                           eos_token_id=eos_token_id)
               for i, p in enumerate(prompts)]
        self.run_until_complete(max_steps)
        return [self.purge(i) for i in ids]

    def prefix_probe(self, prompt) -> int:
        """Longest radix-cached prefix of ``prompt`` in tokens, WITHOUT
        admitting or pinning anything — the cheap affinity signal the
        fleet :class:`~paddle_tpu.serving.router.Router` routes on (0
        when the cache is off, bypassed, or cold)."""
        return self.core.prefix_probe(prompt)

    # ----------------------------------------------------------- metrics
    @property
    def metrics(self) -> ServingMetrics:
        return self.core.metrics

    @property
    def registry(self):
        """The engine's ``obs.MetricsRegistry`` — full instrument dump
        via ``.snapshot()``, Prometheus text via ``.prometheus()``."""
        return self.core.metrics.registry

    @property
    def decode_path(self) -> str:
        """``"fused"`` (Pallas decode-block), ``"tp_fused_block"``
        (the SHARDED Pallas decode block on a tp > 1 mesh —
        kernels/decode_block_tp.py), ``"tp_fused"`` (the
        tensor-parallel fused compute-collective shard_map program) or
        ``"unfused"`` — which decode step this engine compiled
        (resolved once at construction; see docs/serving.md)."""
        return self.core.decode_path

    @property
    def decode_fallback_reason(self):
        """Why ``fused_decode=True`` fell back down the chain
        (``None`` when a fused block path is active or the flag is
        off; under tp > 1 the reason names the REAL failed legality
        gate — kv_heads/batch/ffn tiling, bundle surface, VMEM plan —
        per docs/serving.md's fallback matrix)."""
        return self.core.decode_fallback_reason

    @property
    def tensor_parallel(self) -> int:
        """The engine's tensor-parallel mesh degree (1 = single chip)."""
        return self.core.tensor_parallel

    @property
    def tp_fusion_reason(self):
        """Why a tp > 1 engine fell back from the fused
        compute-collective decode to the composed GSPMD path (``None``
        when ``tp_fused`` is active or the engine is single-chip)."""
        return self.core.tp_fusion_reason

    @property
    def spec_k(self) -> int:
        """The requested speculative draft length (0 = speculation
        off).  ``spec_on``/``spec_fallback_reason`` report what the
        engine actually resolved."""
        return self.core.spec_k

    @property
    def spec_on(self) -> bool:
        """Is speculative decoding ACTIVE — requested (``spec_k > 0``),
        resolved viable at construction, and not disabled by the
        degradation ladder since."""
        return self.core.spec_on and not self.core.spec_bypass

    @property
    def spec_fallback_reason(self):
        """Why speculation is off (``None`` while active): the
        construction-time resolution reason, or ``"degraded: ..."``
        when the ladder's ``spec_verify`` rung disabled it mid-run
        (docs/serving.md fallback matrix)."""
        return self.core.spec_fallback_reason

    @property
    def spec_acceptance_rate(self):
        """Accepted / drafted over the current metrics window (None
        before the first speculative step)."""
        return self.core.metrics.spec_acceptance_rate

    @property
    def aot_status(self):
        """Warm-load outcome when an AOT store was attached: ``"warm"``
        (every program loaded), ``"partial"`` (some artifacts degraded
        to trace-on-demand), ``"empty"`` (matched store held no usable
        leg), ``"skew"`` (fingerprint mismatch — fully traced) or
        ``None`` (no store attached).  See docs/serving.md "Zero cold
        start" for the fallback matrix."""
        return self.core.aot_status

    @property
    def tracer(self):
        """The engine's ``obs.Tracer`` — request-lifecycle spans and the
        compile/eviction/skip event log; ``.chrome_events()`` exports
        request lanes for ``profiler.export_chrome_tracing`` merges."""
        return self.core.metrics.tracer

    @property
    def health(self):
        """The engine's :class:`~paddle_tpu.serving.health.EngineHealth`
        state machine (``.state`` is ``healthy | degraded | quarantined
        | circuit_open``); see docs/serving.md "Fault tolerance"."""
        return self.core.health

    @property
    def degraded_subsystems(self):
        """Optional subsystems the degradation ladder has disabled
        (subset of ``("prefix_cache", "chunked_prefill",
        "fused_decode", "spec_verify")``; empty = full service)."""
        return self.core.ladder.disabled_subsystems

    def close(self) -> None:
        """Detach this engine's telemetry from process-global hooks (the
        profiler chrome-export source ``record_events=True`` registered).
        Long-lived processes that churn engines must close them, or every
        later trace export merges the dead engines' lanes too."""
        self.core.metrics.close()

    def metrics_dict(self) -> dict:
        out = self.core.metrics.snapshot()
        if self.core.prefix_cache is not None:
            # lifetime radix-cache state (block occupancy, evictions) —
            # unlike the engine counters these survive metrics.reset()
            out["prefix_cache"] = self.core.prefix_cache.stats()
        # lifetime slot churn (KVPool free-list traffic) — same reset
        # semantics as the prefix-cache block
        out["slot_churn"] = {"allocs": self.core.pool.alloc_count,
                             "frees": self.core.pool.free_count}
        return out
