"""Radix prefix cache: reuse KV context across requests that share a
prompt prefix.

Production traffic is prefix-heavy — system prompts, few-shot templates,
multi-turn history — yet a slot-pooled engine that prefills every prompt
from token 0 recomputes the shared prefix for every arrival.  This
module eliminates that recompute: a HOST-SIDE radix tree over prompt
token ids maps block-aligned prefixes to rows of a second fixed-shape KV
slab (``kv_pool.BlockPool``), so admission

  1. matches the longest cached prefix (block granularity),
  2. gathers the matched block rows into the request's staging cache
     with ONE compiled program (``BlockPool.load_row`` — no recompute,
     no reallocation), and
  3. prefills ONLY the uncached suffix at its pow2 bucket.

Tree shape: each edge carries exactly ``block_len`` token ids (the block
key), each node owns exactly one block row — a radix tree quantised to
block granularity, which is what makes node<->device-row ownership
one-to-one and the device copies fixed-shape.  All tree state is plain
host data: matching/insertion never touch the device except through the
two jitted block-copy programs.

Tensor parallelism (serving/tp.py) changes NOTHING here: the tree is
host state, and under a mesh both slabs shard on the SAME kv-head axis,
so the gather/scatter programs move each device's head shard of a block
to the same device's head shard of the slot — GSPMD partitions the two
copy programs with zero cross-device traffic.

Lifecycle:
  * ``match()``   pins the matched path (refcount +1 per node) until the
    engine calls ``release()`` at request finish — a pinned block can
    never be evicted while a live request's admission copied from it;
  * ``insert()``  walks the prompt's full blocks after its prefill
    completes, copies the freshly computed blocks out of the request's
    pool slot (``BlockPool.store_row``) and extends the tree; when the
    block pool is exhausted it evicts LRU refcount-0 LEAVES, and if
    nothing is evictable it degrades gracefully to a partial (prefix of
    the prompt) insert — correctness never depends on an insert landing;
  * the last prompt token is NEVER served from cache: admission must
    prefill at least one suffix token to produce the logits the first
    sampled token comes from, so ``match()`` caps at
    ``(prompt_len - 1) // block_len`` blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from .kv_pool import BlockPool, KVPool

__all__ = ["PrefixCache", "MatchResult"]


@dataclasses.dataclass
class MatchResult:
    """A pinned prefix match: ``tokens`` matched token count (a multiple
    of ``block_len``; 0 = miss), ``blocks`` the matched block ids in
    prefix order.  Hold it for the request's lifetime and hand it back to
    :meth:`PrefixCache.release` exactly once."""
    tokens: int
    blocks: List[int]
    _nodes: list = dataclasses.field(default_factory=list, repr=False)
    _released: bool = dataclasses.field(default=False, repr=False)


class _Node:
    __slots__ = ("key", "block", "parent", "children", "refcount",
                 "last_use")

    def __init__(self, key: Optional[bytes], block: Optional[int],
                 parent: Optional["_Node"]):
        self.key = key            # block_len token ids, as bytes
        self.block = block        # BlockPool row this node owns
        self.parent = parent
        self.children: Dict[bytes, _Node] = {}
        self.refcount = 0         # live requests pinning this node
        self.last_use = 0         # LRU tick


class PrefixCache:
    """Host radix tree + block-pool accounting.  One per engine; the
    engine is the only caller (``serving.engine.EngineCore``)."""

    def __init__(self, pool: BlockPool):
        self.pool = pool
        self.block_len = pool.block_len
        self.max_match_blocks = pool.blocks_per_row
        self.root = _Node(None, None, None)
        self._tick = 0
        # optional event sink ``fn(name, **attrs)`` — the engine points
        # this at its tracer so LRU evictions land in the event log
        self.on_event = None
        # chaos hook (serving/faults.py): None in production
        self.faults = None
        # observability (engine merges these into its metrics snapshot)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0
        self.inserted_blocks = 0

    # ----------------------------------------------------------- helpers
    def _block_keys(self, tokens, n_blocks: int) -> List[bytes]:
        toks = np.asarray(tokens, np.int32).reshape(-1)
        bl = self.block_len
        return [toks[i * bl:(i + 1) * bl].tobytes()
                for i in range(n_blocks)]

    def _matchable_blocks(self, prompt_len: int) -> int:
        # at least ONE token must prefill (its logits seed sampling), and
        # a match never exceeds one slot row of blocks
        return min((prompt_len - 1) // self.block_len,
                   self.max_match_blocks)

    def _bump(self, node: _Node) -> None:
        self._tick += 1
        node.last_use = self._tick

    # ------------------------------------------------------------- match
    def match_length(self, tokens) -> int:
        """Peek: matched token count for ``tokens`` without pinning
        anything (admission-cost estimates, scheduler budget checks)."""
        n = 0
        node = self.root
        toks = np.asarray(tokens, np.int32).reshape(-1)
        for key in self._block_keys(toks, self._matchable_blocks(len(toks))):
            child = node.children.get(key)
            if child is None:
                break
            node = child
            n += self.block_len
        return n

    def match(self, tokens, count_stats: bool = True) -> MatchResult:
        """Longest cached block-aligned prefix of ``tokens``; pins every
        node on the path (refcount +1) until :meth:`release`.

        The pin is what makes the fleet KV handoff (serving/handoff.py)
        safe: the prefill replica's exported blocks stay pinned — never
        LRU-evictable — for the whole staged->committed/aborted window,
        even though no request on THIS engine holds them.  Handoff
        exports pass ``count_stats=False`` so the transfer walk does not
        inflate the admission hit/miss telemetry."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        path: List[_Node] = []
        node = self.root
        for key in self._block_keys(toks, self._matchable_blocks(len(toks))):
            child = node.children.get(key)
            if child is None:
                break
            node = child
            path.append(node)
        for n in path:
            n.refcount += 1
            self._bump(n)
        matched = len(path) * self.block_len
        if count_stats:
            if path:
                self.hits += 1
                self.hit_tokens += matched
            else:
                self.misses += 1
        return MatchResult(tokens=matched,
                           blocks=[n.block for n in path], _nodes=path)

    def release(self, mr: MatchResult) -> None:
        """Unpin a match (idempotent): the request holding it finished."""
        if mr._released:
            return
        mr._released = True
        for n in mr._nodes:
            if n.refcount <= 0:
                raise RuntimeError(
                    "prefix-cache refcount underflow (double release?)")
            n.refcount -= 1

    # ------------------------------------------------------------- load
    def load_staging(self, mr: MatchResult):
        """Gather the matched blocks into fresh per-layer
        ``[1, max_seq, h, d]`` staging rows (one compiled program)."""
        idx = np.zeros((self.max_match_blocks,), np.int32)
        idx[:len(mr.blocks)] = mr.blocks
        return self.pool.load_row(idx)

    # ------------------------------------------------------------ insert
    def insert(self, tokens, kv_pool: KVPool, slot: int) -> int:
        """Cache the full blocks of ``tokens`` whose KV now sits in
        ``kv_pool`` slot ``slot`` (prefill just completed).  Existing
        path nodes are reused (and touched for LRU); new nodes allocate
        block rows, evicting LRU unpinned leaves when the pool is full.
        Returns the number of NEW blocks written (0 = fully cached
        already, or nothing evictable)."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        n_full = min(len(toks) // self.block_len, self.pool.blocks_per_row)
        dest = np.full((self.pool.blocks_per_row,), self.pool.num_blocks,
                       np.int32)                      # OOB = dropped
        node = self.root
        new = 0
        # transient pin: eviction pressure from THIS insert must never
        # take a node on the path being inserted (an LRU pass could
        # otherwise reap the leaf created two iterations ago, aliasing
        # two dest entries onto one block row)
        pinned: List[_Node] = []
        try:
            for j, key in enumerate(self._block_keys(toks, n_full)):
                child = node.children.get(key)
                if child is None:
                    block = self._alloc_block()
                    if block is None:
                        break                         # graceful partial
                    child = _Node(key, block, node)
                    node.children[key] = child
                    dest[j] = block
                    new += 1
                child.refcount += 1
                pinned.append(child)
                self._bump(child)
                node = child
        finally:
            for n in pinned:
                n.refcount -= 1
        if new:
            self.pool.store_row(kv_pool, slot, dest)
            self.inserted_blocks += new
        return new

    # ---------------------------------------------------------- eviction
    def _alloc_block(self) -> Optional[int]:
        if self.faults is not None \
                and self.faults.check("block_exhausted") is not None:
            return None      # injected exhaustion: graceful partial path
        if self.pool.free_blocks:
            return self.pool.alloc()
        victim = self._lru_unpinned_leaf()
        if victim is None:
            return None
        self._evict(victim)
        return self.pool.alloc()

    def _lru_unpinned_leaf(self) -> Optional[_Node]:
        best = None
        stack = list(self.root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.refcount == 0:
                if best is None or n.last_use < best.last_use:
                    best = n
        return best

    def _evict(self, node: _Node) -> None:
        """Drop a leaf: return its block row to the pool and unlink.  The
        stale device row needs no scrub — nothing references a block the
        tree no longer reaches, and the next occupant overwrites it."""
        assert not node.children and node.refcount == 0
        del node.parent.children[node.key]
        self.pool.free(node.block)
        self.evictions += 1
        if self.on_event is not None:
            self.on_event("prefix_evict", block=node.block,
                          last_use=node.last_use)

    # ------------------------------------------------------------- state
    @property
    def cached_blocks(self) -> int:
        return self.pool.used_blocks

    def stats(self) -> Dict[str, int]:
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_cached_blocks": self.cached_blocks,
            "prefix_evictions": self.evictions,
            "prefix_inserted_blocks": self.inserted_blocks,
        }
