"""Host-side n-gram draft tables for speculative decoding.

The speculative path (docs/serving.md "Speculative decoding") keeps the
engine's fixed-shape discipline intact by splitting the work in two:

  * **draft** (this module, pure host): one :class:`NGramDraftTable`
    per in-flight request proposes up to ``spec_k`` next tokens from an
    order-2/3 suffix lookup over the request's OWN committed tokens
    (prompt + everything already emitted).  The table is seeded from
    the prompt at admission and updated at harvest time — strictly off
    the hot path, after the step's single device readback.
  * **verify** (engine ``_build_verify_fn``): ONE batched
    ``[num_slots, spec_k+1]`` program runs the model over every slot's
    draft window at its own ``seq_pos`` and commits the longest
    verified prefix plus one bonus token.

Chained greedy lookup: ``propose`` walks the table token by token —
the trigram successor of the last two committed tokens when one was
recorded, the bigram successor of the last token otherwise — feeding
each prediction back in as context, so one table hit can draft a whole
``spec_k`` window (the shared-prefix chat workloads the bench models
are exactly the repetitive-suffix traffic this wins on).  Most-recent
occurrence wins on conflict: recency tracks the request's local
phrasing better than frequency for the short horizons involved.

Constrained decoding composes at the draft tier too: a proposal stops
at the first token outside the request's ``allowed_tokens`` set, since
the verify program's vocab mask would reject it anyway — under an
unsatisfiable mask the table simply stops proposing and the slot rides
the normal one-token path (per-slot speculation auto-disable).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["NGramDraftTable"]


class NGramDraftTable:
    """Order-2/3 suffix-lookup draft table over one request's tokens.

    Pure host state — a bigram map ``last -> next``, a trigram map
    ``(prev, last) -> next`` and the two-token context tail.  All
    methods are O(1) per token; the engine calls :meth:`observe` once
    per committed token and :meth:`propose` once per step.
    """

    __slots__ = ("_bi", "_tri", "_ctx")

    def __init__(self):
        self._bi: Dict[int, int] = {}
        self._tri: Dict[Tuple[int, int], int] = {}
        # (prev, last) committed-token context; None = not yet seen
        self._ctx: Tuple[Optional[int], Optional[int]] = (None, None)

    def __len__(self) -> int:
        return len(self._bi) + len(self._tri)

    def seed(self, tokens) -> None:
        """Record the prompt (or any committed token run) in order."""
        for t in tokens:
            self.observe(int(t))

    def observe(self, tok: int) -> None:
        """Record ONE committed token: the previous context now predicts
        it (most-recent occurrence wins), and the context advances."""
        tok = int(tok)
        prev, last = self._ctx
        if last is not None:
            self._bi[last] = tok
            if prev is not None:
                self._tri[(prev, last)] = tok
        self._ctx = (last, tok)

    def propose(self, k: int, allowed=None) -> List[int]:
        """Up to ``k`` draft tokens continuing the committed sequence —
        a chained greedy walk preferring the trigram successor over the
        bigram one, stopped at the first miss (or, with an ``allowed``
        token set, the first out-of-set prediction).  Returns [] when
        the table has no prediction: the slot then falls back to the
        normal single-token decode for this step."""
        prev, last = self._ctx
        out: List[int] = []
        while len(out) < k:
            nxt = self._tri.get((prev, last)) if prev is not None \
                else None
            if nxt is None and last is not None:
                nxt = self._bi.get(last)
            if nxt is None or (allowed is not None
                               and nxt not in allowed):
                break
            out.append(nxt)
            prev, last = last, nxt
        return out
