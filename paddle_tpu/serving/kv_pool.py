"""Slot-pooled KV cache for continuous batching.

One fixed allocation ``[num_slots, max_seq, kv_heads, head_dim]`` per
layer per k/v holds EVERY in-flight request's context; a slot is one
request's row.  The pool never reallocates: admission writes a freshly
prefilled context into a free slot (``adopt``), eviction just returns the
slot index to the free list (the stale rows are overwritten by the next
occupant — and masked until then by the per-slot ``seq_lens`` feeding the
ragged decode-attention kernel, kernels/decode_attention.py).

The pool's per-layer view ``(k, v, pos_vector)`` is EXACTLY the models'
functional cache tuple with a per-row position (models/kv_cache.py), so
``model.decode_step`` runs over all slots unchanged — one fixed-shape
compiled program regardless of which slots are live.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..models.kv_cache import gather_block_rows, scatter_block_rows

__all__ = ["KVPool", "BlockPool"]

# graftmem marker (tools/analysis/memory.py): every slab extent in the
# pool constructors below must flow from registered capacity fields —
# the derived blocks-per-row ratio is declared here so the capacity
# manifest can name it alongside the constructor parameters
__memory_capacity_fields__ = ("blocks_per_row",)


@functools.partial(jax.jit, donate_argnums=(0,))
def _adopt_row(buf, row, slot):
    """Write a [1, max_seq, h, d] prefilled row into slab row ``slot``.
    One compiled program per (shape, dtype) — ``slot`` stays traced."""
    return jax.lax.dynamic_update_slice(buf, row, (slot, 0, 0, 0))


class KVPool:
    """Fixed-shape KV slab + free-list slot accounting.

    Device state:
      * ``ks/vs``   — per-layer [num_slots, max_seq, kv_heads, head_dim];
      * ``seq_pos`` — [num_slots] int32, each slot's current cache length
        (the per-row ``pos`` the models append at AND the ``seq_lens`` the
        ragged attention masks by, after the in-step +1).

    Host state: the free list.  Alloc/free/reset are host-side list ops —
    no device sync, no reallocation.
    """

    def __init__(self, num_slots: int, max_seq: int, num_layers: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32,
                 mesh=None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if mesh is not None and kv_heads % mesh.devices.size:
            raise ValueError(
                f"kv_heads {kv_heads} must divide evenly over the "
                f"{mesh.devices.size}-device tensor-parallel mesh (the "
                f"slot slabs partition on the kv-head axis)")
        self.num_slots = num_slots
        self.max_seq = max_seq
        self.num_layers = num_layers
        self.mesh = mesh
        shape = (num_slots, max_seq, kv_heads, head_dim)
        if mesh is None:
            self.ks: List[jax.Array] = [jnp.zeros(shape, dtype)
                                        for _ in range(num_layers)]
            self.vs: List[jax.Array] = [jnp.zeros(shape, dtype)
                                        for _ in range(num_layers)]
            self.seq_pos = jnp.zeros((num_slots,), jnp.int32)
        else:
            # tensor-parallel serving (serving/tp.py): slabs partition
            # on the kv-head axis, the position vector replicates —
            # every compiled program touching the pool then compiles
            # against the sharded layout.  Born SHARDED (jit with
            # out_shardings), never materialized whole on one device:
            # at pod scale the full slab may not fit a single chip —
            # that is the point of sharding it
            from .tp import sharded_zeros, replicated
            mk = sharded_zeros(mesh, shape, dtype)
            self.ks = [mk() for _ in range(num_layers)]
            self.vs = [mk() for _ in range(num_layers)]
            self.seq_pos = replicated(
                jnp.zeros((num_slots,), jnp.int32), mesh)
        self._free: List[int] = list(range(num_slots - 1, -1, -1))
        # lifetime slot-churn counters (telemetry: metrics_dict reports
        # them; high churn relative to finished requests = thrashing)
        self.alloc_count = 0
        self.free_count = 0
        # chaos hook (serving/faults.py): None in production — the only
        # overhead when off is this attribute test in alloc()
        self.faults = None

    @classmethod
    def create(cls, model, num_slots: int,
               max_seq: Optional[int] = None, mesh=None) -> "KVPool":
        """Size the pool from a causal-LM's config (kv_heads falls back
        to num_heads for MHA models like GPT).  With ``mesh`` the slabs
        lay out kv-head-sharded over the tensor-parallel mesh."""
        cfg = model.cfg
        max_seq = max_seq or cfg.max_seq_len
        kv_heads = getattr(cfg, "kv_heads", None) or cfg.num_heads
        return cls(num_slots, max_seq, cfg.num_layers, kv_heads,
                   cfg.head_dim, dtype=jnp.dtype(cfg.dtype), mesh=mesh)

    # ------------------------------------------------------------ slots
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return self.num_slots - len(self._free)

    def alloc(self) -> int:
        """Claim a free slot (lowest index first, so slot churn reuses a
        warm row).  Raises if the pool is full — the scheduler gates
        admission on ``free_slots``."""
        if self.faults is not None:
            self.faults.fire("kv_alloc")
        if not self._free:
            raise RuntimeError("KVPool exhausted: no free slot")
        self.alloc_count += 1
        return self._free.pop()

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        if slot in self._free:
            raise ValueError(f"slot {slot} already free (double free)")
        self.free_count += 1
        self._free.append(slot)
        self._free.sort(reverse=True)
        # park the freed row at position 0 so its ride-along decode writes
        # stay at the row head (bounded) until the next adopt overwrites it
        self.seq_pos = self.seq_pos.at[slot].set(0)

    def reset(self) -> None:
        """Return every slot to the free list; buffers stay allocated
        (stale rows are masked by seq_pos=0 until overwritten)."""
        self._free = list(range(self.num_slots - 1, -1, -1))
        self.seq_pos = jnp.zeros((self.num_slots,), jnp.int32)
        if self.mesh is not None:
            from .tp import replicated
            self.seq_pos = replicated(self.seq_pos, self.mesh)

    def adopt(self, slot: int, layer_caches, length: int,
              set_pos: bool = True) -> None:
        """Move a freshly prefilled single-request cache (per-layer
        ``(k [1, max_seq, h, d], v, _)`` tuples) into ``slot`` and record
        its ``length`` valid positions.  The copy is a jitted
        dynamic_update_slice with a traced slot index — admitting to a
        different slot never recompiles.

        ``set_pos=False`` skips the position write: the fleet KV handoff
        (serving/handoff.py) stages transferred rows through a transient
        slot purely as the scatter program's source — no decode ever
        reads the slot, so updating (and then re-zeroing) ``seq_pos``
        would be two wasted device ops per transfer."""
        s = jnp.asarray(slot, jnp.int32)
        for i, layer in enumerate(layer_caches):
            self.ks[i] = _adopt_row(self.ks[i], layer[0], s)
            self.vs[i] = _adopt_row(self.vs[i], layer[1], s)
        if set_pos:
            self.seq_pos = self.seq_pos.at[slot].set(length)

    # ------------------------------------------------------- cache views
    def caches(self) -> List[Tuple[jax.Array, jax.Array, jax.Array]]:
        """The models' cache pytree over all slots: per-layer
        ``(k, v, seq_pos)`` with the SHARED per-slot position vector."""
        return [(k, v, self.seq_pos) for k, v in zip(self.ks, self.vs)]

    def update(self, new_caches) -> None:
        """Absorb the cache pytree a decode step returned (every layer
        advanced the shared position vector identically — keep layer 0's)."""
        self.ks = [c[0] for c in new_caches]
        self.vs = [c[1] for c in new_caches]
        self.seq_pos = new_caches[0][2]


class BlockPool:
    """The SECOND fixed-shape KV slab: per-layer
    ``[num_blocks, block_len, kv_heads, head_dim]`` block rows holding
    cached PREFIX context, shared across requests.  The radix tree
    (serving/prefix_cache.py) owns which block holds which token span —
    this class owns only the device memory and the two compiled copy
    programs:

      * ``load_row(idx)``   — gather ``max_seq // block_len`` block rows
        into a fresh ``[1, max_seq]`` cache row (the staging cache a
        matched request prefills its suffix into).  ``idx`` is traced row
        data padded arbitrarily past the true match count (stale gathers
        are masked downstream by ``seq_lens``), so ONE program serves
        every match length;
      * ``store_row(ks, vs, slot, dest)`` — split pool slot ``slot``'s
        row into blocks and scatter block j to ``dest[j]``; ``dest``
        entries set to ``num_blocks`` are out-of-bounds and DROPPED, so
        the same single program writes any subset of a prompt's blocks.

    Like ``KVPool``, buffers never reallocate; block lifecycle (alloc /
    free / refcount / LRU) is host-side list accounting.
    """

    def __init__(self, num_blocks: int, block_len: int, max_seq: int,
                 num_layers: int, kv_heads: int, head_dim: int,
                 dtype=jnp.float32, mesh=None):
        if num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        if block_len < 1:
            raise ValueError("block_len must be >= 1")
        if max_seq % block_len:
            raise ValueError(
                f"block_len {block_len} must divide max_seq {max_seq} "
                f"(block boundaries must tile the slot row)")
        self.num_blocks = num_blocks
        self.block_len = block_len
        self.max_seq = max_seq
        self.num_layers = num_layers
        self.mesh = mesh
        self.blocks_per_row = max_seq // block_len
        shape = (num_blocks, block_len, kv_heads, head_dim)
        if mesh is None:
            self.bks: List[jax.Array] = [jnp.zeros(shape, dtype)
                                         for _ in range(num_layers)]
            self.bvs: List[jax.Array] = [jnp.zeros(shape, dtype)
                                         for _ in range(num_layers)]
        else:
            # radix block slab partitions on the SAME kv-head axis as
            # the slot slabs (so the gather/scatter copy programs move
            # blocks without cross-device traffic), and is likewise
            # born sharded — never whole on one device
            from .tp import sharded_zeros
            mk = sharded_zeros(mesh, shape, dtype)
            self.bks = [mk() for _ in range(num_layers)]
            self.bvs = [mk() for _ in range(num_layers)]
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self.trace_counts = {"gather": 0, "scatter": 0}
        self._load_fn = None
        self._store_fn = None
        # chaos hook (serving/faults.py): None in production
        self.faults = None

    @classmethod
    def create(cls, model, num_blocks: int, block_len: int,
               max_seq: int, mesh=None) -> "BlockPool":
        cfg = model.cfg
        kv_heads = getattr(cfg, "kv_heads", None) or cfg.num_heads
        return cls(num_blocks, block_len, max_seq, cfg.num_layers,
                   kv_heads, cfg.head_dim, dtype=jnp.dtype(cfg.dtype),
                   mesh=mesh)

    # ------------------------------------------------------------ blocks
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - len(self._free)

    def alloc(self) -> int:
        if self.faults is not None:
            self.faults.fire("block_alloc")
        if not self._free:
            raise RuntimeError("BlockPool exhausted: no free block")
        return self._free.pop()

    def free(self, block: int) -> None:
        if not 0 <= block < self.num_blocks:
            raise ValueError(f"block {block} out of range")
        if block in self._free:
            raise ValueError(f"block {block} already free (double free)")
        self._free.append(block)

    # ---------------------------------------------------- copy programs
    def _build_load_fn(self):
        """The gather program factory — shared by the lazy trace in
        :meth:`load_row` and the AOT builder (serving/aot.py), so the
        exported artifact and the traced program are one body."""
        def load(bks, bvs, idx):
            self.trace_counts["gather"] += 1   # trace-time tick
            ks = [gather_block_rows(b, idx)[None] for b in bks]
            vs = [gather_block_rows(b, idx)[None] for b in bvs]
            return ks, vs

        return jax.jit(load)

    def _build_store_fn(self):
        """The scatter program factory (same sharing contract as
        :meth:`_build_load_fn`)."""
        n = (1, self.max_seq) + self.bks[0].shape[2:]

        def store(bks, bvs, ks, vs, slot, dest):
            self.trace_counts["scatter"] += 1  # trace-time tick
            start = (slot, 0, 0, 0)
            new_bks = [
                scatter_block_rows(
                    b, jax.lax.dynamic_slice(k, start, n)[0], dest)
                for b, k in zip(bks, ks)]
            new_bvs = [
                scatter_block_rows(
                    b, jax.lax.dynamic_slice(v, start, n)[0], dest)
                for b, v in zip(bvs, vs)]
            return new_bks, new_bvs

        return jax.jit(store, donate_argnums=(0, 1))

    def load_row(self, idx) -> Tuple[List[jax.Array], List[jax.Array]]:
        """Gather blocks ``idx`` ([blocks_per_row] int32, padded past the
        match with any in-bounds value) into per-layer ``[1, max_seq, h,
        d]`` staging rows."""
        if self.faults is not None:
            self.faults.fire("gather")
        if self._load_fn is None:
            self._load_fn = self._build_load_fn()
        return self._load_fn(self.bks, self.bvs,
                             jnp.asarray(idx, jnp.int32))

    def store_row(self, pool: KVPool, slot: int, dest) -> None:
        """Scatter pool slot ``slot``'s row into block rows ``dest``
        ([blocks_per_row] int32; entries == num_blocks are dropped).
        Donates the block slabs — cache memory stays one allocation."""
        if self.faults is not None:
            self.faults.fire("scatter")
        if self._store_fn is None:
            self._store_fn = self._build_store_fn()
        self.bks, self.bvs = self._store_fn(
            self.bks, self.bvs, pool.ks, pool.vs,
            jnp.asarray(slot, jnp.int32), jnp.asarray(dest, jnp.int32))
