"""Request admission + FCFS queue + prefill/decode interleaving policy.

The scheduler is pure host-side control plane: it owns the waiting queue,
the slot -> request map, and the BUCKETING policy that keeps the compile
cache bounded.  Nothing here touches device arrays — the engine asks
"what should run this step" and the scheduler answers with host ints.

Bucketing: prefill runs at the prompt's length rounded UP to a power of
two (floor ``min_bucket``), so a mixed-length workload lowers at most
``O(log2(max_seq / min_bucket))`` distinct prefill programs instead of
one per length — graftlint's recompile-hazard rule applied to serving.
With chunked prefill (``chunk_plan``) the suffix instead runs as fixed
``prefill_chunk``-token pieces plus one bucketed tail, interleaved with
decode at step granularity.  Decode is always the single
``[num_slots, 1]`` program.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["SamplingParams", "Request", "Scheduler", "bucket_length",
           "PRIORITIES"]

DEFAULT_MIN_BUCKET = 16

# request priority classes (docs/serving.md "Tail latency"):
# "interactive" is the latency-sensitive default; "batch" is offline
# work the admission window may defer behind interactive arrivals and
# the fleet brownout sheds FIRST under sustained overload
PRIORITIES = ("interactive", "batch")


def bucket_length(n: int, min_bucket: int = DEFAULT_MIN_BUCKET,
                  max_len: Optional[int] = None) -> int:
    """Smallest power-of-two >= ``n`` (floored at ``min_bucket``, capped
    at ``max_len``).  The cap may round DOWN below the pow2 — a prompt of
    0.9*max_seq still pads only to max_len, never past the cache."""
    if n < 1:
        raise ValueError("length must be >= 1")
    if max_len is not None and n > max_len:
        raise ValueError(f"length {n} exceeds max_len {max_len}")
    b = max(min_bucket, 1)
    while b < n:
        b *= 2
    if max_len is not None:
        b = min(b, max_len)
    return b


@dataclasses.dataclass
class SamplingParams:
    """Per-request decode policy.  ``do_sample=False`` is greedy (the
    temperature/top_k/top_p knobs are then inert); sampling applies
    temperature, then top-k (0 = off), then top-p (1.0 = off) — the same
    order and semantics as ``models.generation.generate``."""
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def validate(self) -> None:
        if self.do_sample and self.temperature <= 0:
            raise ValueError("temperature must be > 0 when sampling")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must lie in (0, 1]")


@dataclasses.dataclass
class Request:
    """One in-flight generation request (control-plane state; the KV
    context lives in the pool slot while the request is running)."""
    request_id: int
    prompt: np.ndarray                       # [prompt_len] int token ids
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: Optional[int] = None
    stream: Optional[object] = None          # callable(request, token)
    # priority class ("interactive" | "batch"): interactive is the
    # latency-sensitive default; batch is deferrable offline work —
    # admission prefers interactive inside the bounded skip window and
    # the fleet brownout sheds batch first (docs/serving.md)
    priority: str = "interactive"
    arrival_time: float = 0.0
    # robustness surface (docs/serving.md "Fault tolerance"): deadlines
    # are seconds RELATIVE to submission, checked host-side per step
    deadline_s: Optional[float] = None       # submit -> finish budget
    ttft_deadline_s: Optional[float] = None  # submit -> first token
    # constrained decoding (docs/serving.md "Constrained decoding"):
    # the only token ids this request may emit, applied as a per-slot
    # vocab mask INSIDE the existing decode/verify programs (a traced
    # operand — zero new compiled programs); None = unconstrained
    allowed_tokens: Optional[np.ndarray] = None
    # engine-owned progress
    tokens: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None      # "eos" | "length"
    # terminal disposition — every request ends with exactly one:
    # "finished" | "cancelled" | "deadline_exceeded" | "rejected" |
    # "failed" (None only while in flight); status_reason carries the
    # human-readable why ("eos", "ttft deadline 0.05s exceeded", ...)
    status: Optional[str] = None
    status_reason: Optional[str] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    admit_time: Optional[float] = None       # queue exit (telemetry)
    last_token_time: Optional[float] = None  # previous emit (TPOT)
    prefix_hit_tokens: int = 0               # prompt tokens served from
    prefill_chunks: int = 0                  # the radix cache / chunks run

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    def deadline_violation(self, now: float) -> Optional[str]:
        """The deadline this request has blown at host time ``now``
        (perf_counter base), or None.  End-to-end is checked first —
        it subsumes TTFT once tokens flow."""
        if self.deadline_s is not None \
                and now - self.arrival_time > self.deadline_s:
            return (f"end-to-end deadline {self.deadline_s}s exceeded "
                    f"({len(self.tokens)} tokens generated)")
        if self.first_token_time is None \
                and self.ttft_deadline_s is not None \
                and now - self.arrival_time > self.ttft_deadline_s:
            return f"TTFT deadline {self.ttft_deadline_s}s exceeded"
        return None


class Scheduler:
    """FCFS admission over a fixed slot budget, with a BOUNDED
    head-of-line escape hatch.

    ``admit()`` pops waiting requests in arrival order while free slots
    (and the optional per-step prefill token budget) remain — the engine
    prefills each admitted request and then runs ONE decode step over all
    occupied slots, so prefill and decode interleave at step granularity.

    Head-of-line fix: when the head request's prefill cost (its UNCACHED
    suffix bucket — the ``cost`` callable, prefix-cache aware) exceeds
    the remaining token budget but a later queued request fits, the later
    one is admitted instead of idling free slots.  The skip is bounded
    two ways: only the first ``skip_window`` queue positions are eligible
    to jump, and after ``max_head_skips`` total jumps over the same head
    the window collapses to the head alone — admission then waits for the
    budget the head needs, so no request starves."""

    def __init__(self, num_slots: int, max_seq: int,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_prefills_per_step: Optional[int] = None,
                 skip_window: int = 4, max_head_skips: int = 16):
        self.num_slots = num_slots
        self.max_seq = max_seq
        self.min_bucket = min_bucket
        # None = admit as many as slots allow each step; a small cap
        # trades TTFT of queued requests against decode stalls of the
        # already-running ones (prefill blocks the shared step loop)
        self.max_prefills_per_step = max_prefills_per_step
        self.skip_window = skip_window
        self.max_head_skips = max_head_skips
        self._head_skips = 0
        # lifetime jump count (never reset by a head admission) — the
        # engine turns per-step deltas into head_of_line_skip events
        self.total_head_skips = 0
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}      # slot -> request
        self._ids = itertools.count()

    # -------------------------------------------------------- submission
    def submit(self, req: Request) -> Request:
        req.sampling.validate()
        if req.priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, "
                f"got {req.priority!r}")
        if req.prompt_len < 1:
            raise ValueError("prompt must hold at least one token")
        if req.prompt_len + req.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt_len {req.prompt_len} + max_new_tokens "
                f"{req.max_new_tokens} exceeds the pool max_seq "
                f"{self.max_seq}")
        if req.arrival_time == 0.0:
            req.arrival_time = time.perf_counter()
        self.waiting.append(req)
        return req

    def next_request_id(self) -> int:
        return next(self._ids)

    def start_ids(self, start: int) -> None:
        """Advance the request-id counter so ids begin at ``start`` —
        an engine reopening a durable journal must never reuse an id
        the journal already holds (the ledger would alias two
        requests).  Only legal before any id was handed out."""
        self._ids = itertools.count(start)

    # --------------------------------------------------------- admission
    def bucket(self, prompt_len: int) -> int:
        return bucket_length(prompt_len, self.min_bucket, self.max_seq)

    def chunk_plan(self, start: int, prompt_len: int,
                   prefill_chunk: Optional[int]) -> List[Tuple[int, int, int]]:
        """Split the uncached suffix ``[start, prompt_len)`` into prefill
        chunks: ``(offset, width, valid)`` triples where ``width`` is the
        compiled program's token width and ``valid <= width`` the real
        tokens in the chunk.

        ``prefill_chunk=None`` -> ONE chunk at the suffix's pow2 bucket
        (the pre-chunking behavior).  Otherwise every chunk except the
        last runs at exactly ``prefill_chunk`` tokens and the tail runs
        at its own pow2 bucket (capped at the chunk size), so the
        compiled-program set stays {chunk} + O(log2(prefill_chunk /
        min_bucket)) regardless of prompt lengths — and the engine can
        interleave one chunk per step with the all-slots decode program
        instead of stalling every stream behind a whole-prompt prefill."""
        out: List[Tuple[int, int, int]] = []
        pos = start
        while pos < prompt_len:
            rem = prompt_len - pos
            if prefill_chunk is not None and rem > prefill_chunk:
                w = v = prefill_chunk
            else:
                w = bucket_length(rem, self.min_bucket, self.max_seq - pos)
                if prefill_chunk is not None:
                    w = min(w, prefill_chunk)
                v = rem
            out.append((pos, w, v))
            pos += v
        return out

    def admit(self, free_slots: int, token_budget: Optional[int] = None,
              cost=None) -> List[Tuple[Request, int]]:
        """Pop up to ``free_slots`` (and the per-step prefill cap)
        waiting requests, returning ``(request, prefill_cost)`` pairs.
        Slot indices are assigned by the caller (the pool owns the free
        list).

        ``cost(req)`` is the prefill work the request needs THIS step in
        tokens (the engine passes its prefix-cache-aware suffix bucket,
        capped at one chunk); default: the full-prompt pow2 bucket.
        ``token_budget`` caps the summed cost per call (None = unbounded
        -> pure FCFS).  When the head doesn't fit the remaining budget, a
        later request within ``skip_window`` may jump it — see the class
        docstring for the no-starvation bound."""
        cap = free_slots if self.max_prefills_per_step is None else \
            min(free_slots, self.max_prefills_per_step)
        if cost is None:
            cost = lambda r: self.bucket(r.prompt_len)
        if token_budget is not None and token_budget < 1:
            # a budget the loop gate can never open would silently starve
            # every request (the over-budget head escape sits INSIDE the
            # gate) — reject loudly instead
            raise ValueError(
                f"token_budget must be >= 1, got {token_budget}")
        budget = float("inf") if token_budget is None else int(token_budget)
        out: List[Tuple[Request, int]] = []
        while self.waiting and len(out) < cap and budget > 0:
            window = 1 if self._head_skips >= self.max_head_skips \
                else 1 + self.skip_window
            # priority-aware pick inside the SAME bounded window: the
            # first budget-fitting interactive request wins; a batch
            # request is admitted only when no interactive one fits.
            # The window/head-skip bounds are unchanged, so the
            # no-starvation guarantee holds for batch work too — once
            # max_head_skips jumps collapse the window to the head,
            # even a batch head admits (a batch request can be
            # deferred, never starved)
            pick = batch_pick = None
            for j, req in enumerate(
                    itertools.islice(self.waiting, window)):
                c = cost(req)
                if c <= budget:
                    if req.priority != "batch":
                        pick, pick_cost = j, c
                        break
                    if batch_pick is None:
                        batch_pick = (j, c)
            if pick is None and batch_pick is not None:
                pick, pick_cost = batch_pick
            if pick is None:
                head_cost = cost(self.waiting[0])
                if not out and token_budget is not None \
                        and head_cost > token_budget:
                    # the head exceeds even a FULL step budget, so
                    # deferring it can never end: the budget is a stall
                    # bound, not a correctness bound — admit it anyway
                    # (one over-budget step) instead of idling forever
                    pick, pick_cost = 0, head_cost
                else:
                    break
            if pick == 0:
                self._head_skips = 0
            else:
                self._head_skips += 1
                self.total_head_skips += 1
            req = self.waiting[pick]
            del self.waiting[pick]
            budget -= pick_cost
            out.append((req, pick_cost))
        return out

    def remove_waiting(self, request_id: int) -> Optional[Request]:
        """Pull one request out of the waiting queue by id (cancellation
        / deadline expiry of a not-yet-admitted request); returns it, or
        None when it is not queued."""
        for i, req in enumerate(self.waiting):
            if req.request_id == request_id:
                del self.waiting[i]
                return req
        return None

    def expired_waiting(self, now: float) -> List[Request]:
        """Remove and return every queued request whose deadline has
        already passed at ``now`` — a request that can no longer meet
        its SLO must not consume a slot and a prefill first."""
        out = [r for r in self.waiting
               if r.deadline_violation(now) is not None]
        for r in out:
            self.waiting.remove(r)
        return out

    def requeue_front(self, reqs: List[Request]) -> None:
        """Push admitted-but-not-started requests back onto the HEAD of
        the waiting queue, preserving their relative order — the engine
        uses this when admission fails partway through a batch so no
        popped request is ever lost."""
        for req in reversed(reqs):
            self.waiting.appendleft(req)

    def place(self, req: Request, slot: int) -> None:
        if slot in self.running:
            raise ValueError(f"slot {slot} already occupied")
        self.running[slot] = req

    def release(self, slot: int) -> Request:
        return self.running.pop(slot)

    # ------------------------------------------------------------- state
    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)
