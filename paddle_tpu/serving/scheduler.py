"""Request admission + FCFS queue + prefill/decode interleaving policy.

The scheduler is pure host-side control plane: it owns the waiting queue,
the slot -> request map, and the BUCKETING policy that keeps the compile
cache bounded.  Nothing here touches device arrays — the engine asks
"what should run this step" and the scheduler answers with host ints.

Bucketing: prefill runs at the prompt's length rounded UP to a power of
two (floor ``min_bucket``), so a mixed-length workload lowers at most
``O(log2(max_seq / min_bucket))`` distinct prefill programs instead of
one per length — graftlint's recompile-hazard rule applied to serving.
Decode is always the single ``[num_slots, 1]`` program.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["SamplingParams", "Request", "Scheduler", "bucket_length"]

DEFAULT_MIN_BUCKET = 16


def bucket_length(n: int, min_bucket: int = DEFAULT_MIN_BUCKET,
                  max_len: Optional[int] = None) -> int:
    """Smallest power-of-two >= ``n`` (floored at ``min_bucket``, capped
    at ``max_len``).  The cap may round DOWN below the pow2 — a prompt of
    0.9*max_seq still pads only to max_len, never past the cache."""
    if n < 1:
        raise ValueError("length must be >= 1")
    if max_len is not None and n > max_len:
        raise ValueError(f"length {n} exceeds max_len {max_len}")
    b = max(min_bucket, 1)
    while b < n:
        b *= 2
    if max_len is not None:
        b = min(b, max_len)
    return b


@dataclasses.dataclass
class SamplingParams:
    """Per-request decode policy.  ``do_sample=False`` is greedy (the
    temperature/top_k/top_p knobs are then inert); sampling applies
    temperature, then top-k (0 = off), then top-p (1.0 = off) — the same
    order and semantics as ``models.generation.generate``."""
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def validate(self) -> None:
        if self.do_sample and self.temperature <= 0:
            raise ValueError("temperature must be > 0 when sampling")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must lie in (0, 1]")


@dataclasses.dataclass
class Request:
    """One in-flight generation request (control-plane state; the KV
    context lives in the pool slot while the request is running)."""
    request_id: int
    prompt: np.ndarray                       # [prompt_len] int token ids
    max_new_tokens: int
    sampling: SamplingParams
    eos_token_id: Optional[int] = None
    stream: Optional[object] = None          # callable(request, token)
    arrival_time: float = 0.0
    # engine-owned progress
    tokens: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None      # "eos" | "length"
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])


class Scheduler:
    """FCFS admission over a fixed slot budget.

    ``admit()`` pops waiting requests in arrival order while free slots
    remain — the engine prefills each admitted request (one bucketed
    program) and then runs ONE decode step over all occupied slots, so
    prefill and decode interleave at step granularity."""

    def __init__(self, num_slots: int, max_seq: int,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 max_prefills_per_step: Optional[int] = None):
        self.num_slots = num_slots
        self.max_seq = max_seq
        self.min_bucket = min_bucket
        # None = admit as many as slots allow each step; a small cap
        # trades TTFT of queued requests against decode stalls of the
        # already-running ones (prefill blocks the shared step loop)
        self.max_prefills_per_step = max_prefills_per_step
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}      # slot -> request
        self._ids = itertools.count()

    # -------------------------------------------------------- submission
    def submit(self, req: Request) -> Request:
        req.sampling.validate()
        if req.prompt_len < 1:
            raise ValueError("prompt must hold at least one token")
        if req.prompt_len + req.max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt_len {req.prompt_len} + max_new_tokens "
                f"{req.max_new_tokens} exceeds the pool max_seq "
                f"{self.max_seq}")
        if req.arrival_time == 0.0:
            req.arrival_time = time.perf_counter()
        self.waiting.append(req)
        return req

    def next_request_id(self) -> int:
        return next(self._ids)

    # --------------------------------------------------------- admission
    def bucket(self, prompt_len: int) -> int:
        return bucket_length(prompt_len, self.min_bucket, self.max_seq)

    def admit(self, free_slots: int) -> List[Tuple[Request, int]]:
        """FCFS: pop up to ``free_slots`` (and the per-step prefill cap)
        waiting requests, returning ``(request, prefill_bucket)`` pairs in
        arrival order.  Slot indices are assigned by the caller (the pool
        owns the free list)."""
        cap = free_slots if self.max_prefills_per_step is None else \
            min(free_slots, self.max_prefills_per_step)
        out: List[Tuple[Request, int]] = []
        while self.waiting and len(out) < cap:
            req = self.waiting.popleft()
            out.append((req, self.bucket(req.prompt_len)))
        return out

    def place(self, req: Request, slot: int) -> None:
        if slot in self.running:
            raise ValueError(f"slot {slot} already occupied")
        self.running[slot] = req

    def release(self, slot: int) -> Request:
        return self.running.pop(slot)

    # ------------------------------------------------------------- state
    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)
