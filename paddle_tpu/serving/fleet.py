"""Fleet-level total accounting: the chaos invariant as a library.

PR 8's chaos suite pinned the single-engine invariant — after any
injected fault sequence, every submitted request is terminal with a
reason and the pools return to baseline.  The fleet tier extends it
across N replicas behind a :class:`~paddle_tpu.serving.router.Router`:

  (a) every FLEET request reaches a terminal status with a reason —
      failover may move a request between replicas, but it can never
      lose one;
  (b) every replica's ``KVPool`` free count, ``BlockPool`` block
      accounting and radix-tree refcounts sit at baseline once the
      fleet drains — a fault on one replica never leaks capacity on
      any;
  (c) no request is served twice: a failed-over request's total
      submissions never exceed two (original + one resubmission), and
      the router's delivered high-water mark keeps the client stream
      exactly-once;
  (d) disaggregated fleets additionally conserve the KV handoff: every
      opened handoff reached a terminal state (committed or aborted —
      staged == committed + aborted once drained), so no prefill-side
      radix pin or decode-side staging slot can be outstanding, and the
      per-replica baselines of (b) hold on prefill, decode AND retired
      replicas alike;
  (f) hedged requests (docs/serving.md "Tail latency") additionally
      conserve the RACE: every issued hedge reached a resolution (win
      or purge — no settled request still holds a live hedge record),
      a hedged request's total submissions still respect the
      attempts <= 2 bound of (c), and the loser's unwind left both
      replicas at the baselines of (b);
  (e) journaled fleets (``Router(journal=...)``) additionally conserve
      the LEDGER: every journaled submit record reaches EXACTLY ONE
      terminal record — across process incarnations — and the baselines
      of (b) hold on every SURVIVING replica (a killed replica is a
      dead process; its internals are unreadable by definition and it
      is excluded from the roll-up, which is precisely why the ledger
      check matters: the journal is the only accounting a crash cannot
      destroy).

These helpers compute the verdict as plain dicts so the chaos tests
(``tests/test_zz_fleet_serving.py``), the CI smoke
(``scripts/fleet_chaos_smoke.py``) and operator tooling all read the
same accounting.  Pure host code; call after a drain
(``router.run_until_complete()``) — a mid-flight fleet legitimately
holds slots and pins.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = ["replica_accounting", "fleet_accounting", "TERMINAL_STATUSES"]

TERMINAL_STATUSES = ("finished", "cancelled", "deadline_exceeded",
                     "rejected", "failed")


def replica_accounting(engine) -> Dict[str, object]:
    """One replica's baseline check (a drained
    :class:`~paddle_tpu.serving.api.ServingEngine`): free slots back to
    capacity, block pool conserved, zero radix pins, tree<->pool
    ownership intact, nothing queued or placed.  ``ok`` is the verdict;
    the rest is the diagnosis."""
    core = engine.core
    out: Dict[str, object] = {
        "free_slots": core.pool.free_slots,
        "num_slots": core.num_slots,
        "queue_depth": core.scheduler.queue_depth,
        "active": core.scheduler.active,
        "mid_prefill": len(core._prefills),
        "health": engine.health.state,
        "slow": engine.health.slow,
        "degraded_subsystems": list(engine.degraded_subsystems),
        "quarantines": core.health.quarantine_count,
        "decode_traces": core.trace_counts["decode"],
    }
    slots_ok = (core.pool.free_slots == core.num_slots
                and core.scheduler.active == 0
                and core.scheduler.queue_depth == 0
                and not core._prefills)
    blocks_ok = pins_ok = tree_ok = True
    if core.block_pool is not None:
        bp = core.block_pool
        out["free_blocks"] = bp.free_blocks
        out["used_blocks"] = bp.used_blocks
        blocks_ok = bp.free_blocks + bp.used_blocks == bp.num_blocks
    if core.prefix_cache is not None:
        nodes = 0
        stack = list(core.prefix_cache.root.children.values())
        while stack:
            n = stack.pop()
            if n.refcount != 0:
                pins_ok = False
            nodes += 1
            stack.extend(n.children.values())
        out["radix_nodes"] = nodes
        tree_ok = nodes == core.block_pool.used_blocks
    out["ok"] = bool(slots_ok and blocks_ok and pins_ok and tree_ok)
    if not out["ok"]:
        out["violations"] = [name for name, ok in (
            ("slots", slots_ok), ("blocks", blocks_ok),
            ("radix_pins", pins_ok), ("tree_ownership", tree_ok)) if not ok]
    return out


def fleet_accounting(router) -> Dict[str, object]:
    """The fleet verdict over a drained router: per-request terminal
    statuses (invariant a), per-replica baselines (invariant b), the
    exactly-once bound (invariant c), and — for disaggregated fleets —
    handoff conservation (invariant d).  ``ok`` rolls all four up —
    ``scripts/fleet_chaos_smoke.py`` exits nonzero on False."""
    requests: List[Dict[str, object]] = []
    all_terminal = True
    once_ok = True
    for fid in sorted(router._requests):
        fr = router._requests[fid]
        out = router.result(fid)
        terminal = (out.finished and out.status in TERMINAL_STATUSES
                    and bool(out.status_reason))
        all_terminal &= terminal
        once_ok &= fr.attempts <= 2
        requests.append({
            "fleet_id": fid, "replica": fr.replica,
            "attempts": fr.attempts, "status": out.status,
            "reason": out.status_reason, "tokens": len(out.tokens),
            "delivered": fr.delivered,
            "failed_over": fr.attempts > 1 and not fr.hedged,
            "hedged": fr.hedged,
            "priority": fr.priority,
            "stage": fr.role_stage,
            "handoffs": fr.handoffs,
            # the failover audit trail: which replica surrendered the
            # request and why (empty for never-failed-over requests)
            "history": [{"replica": r, "reason": why}
                        for r, _, why in fr.history],
        })
    replicas = []
    for h in router.replicas:
        if h.killed:
            # a killed replica is a dead process: nothing inside it is
            # readable, so it carries no baseline verdict — invariant
            # (e)'s ledger check is what accounts for its casualties
            replicas.append({"ok": None, "role": h.role,
                             "retired": h.retired, "killed": True})
            continue
        ra = replica_accounting(h.engine)
        ra["role"] = h.role
        ra["retired"] = h.retired
        ra["killed"] = False
        replicas.append(ra)
    surviving_ok = all(r["ok"] for r in replicas if not r["killed"])
    # invariant d: the handoff ledger is conserved — nothing left
    # mid-flight, and every open matched a terminal transition
    mgr = router._handoffs
    handoffs_settled = (mgr.pending == 0
                        and mgr.staged == mgr.committed + mgr.aborted)
    # invariant f: every issued hedge reached a resolution — a settled
    # request still pointing at a live hedge record means the loser
    # was never unwound (its slot and pins are leaked on that replica)
    hedges_settled = all(fr.hedge_rid < 0
                         for fid, fr in router._requests.items()
                         if fid not in router._live)
    # invariant e: journal-ledger conservation — every journaled submit
    # reached exactly one terminal record (across incarnations; the
    # ledger folds every surviving segment).  flush() first so pending
    # retried writes (journal_write chaos) land before the audit.
    journal = getattr(router, "journal", None)
    journal_ok = True
    ledger_summary = None
    if journal is not None:
        journal.flush()
        led = journal.ledger()
        # rows with NO submit record are documented crash artifacts
        # (the submit write died with the process; docs/serving.md's
        # replay matrix: "unreplayable, skipped — nothing strands") —
        # reported as orphans, never as conservation violations
        bad = {rid: v for rid, v in led.items()
               if v["submits"] >= 1 and v["terminals"] != 1}
        orphans = sorted(rid for rid, v in led.items()
                         if v["submits"] == 0)
        journal_ok = not bad
        ledger_summary = {
            "requests": len(led),
            "violations": sorted(bad) if bad else [],
            "orphans": orphans,
            "pending_writes": journal.position()["pending_writes"],
        }
    ok = bool(all_terminal and once_ok and handoffs_settled
              and hedges_settled and journal_ok and surviving_ok)
    return {
        "ok": ok,
        "all_terminal": bool(all_terminal),
        "served_at_most_once_retry": bool(once_ok),
        "pools_at_baseline": surviving_ok,
        "handoffs_settled": bool(handoffs_settled),
        "hedges_settled": bool(hedges_settled),
        "hedges": router.metrics.c_hedges.value,
        "hedge_wins": router.metrics.c_hedge_wins.value,
        "handoffs_staged": mgr.staged,
        "handoffs_committed": mgr.committed,
        "handoffs_aborted": mgr.aborted,
        "handoff_blocks_moved": mgr.blocks_moved,
        "journal_conserved": bool(journal_ok),
        "journal_ledger": ledger_summary,
        "killed_replicas": sum(1 for r in replicas if r["killed"]),
        "requests": requests,
        "replicas": replicas,
        "failovers": router.metrics.c_failovers.value,
        "failovers_exhausted":
            router.metrics.c_failover_exhausted.value,
    }
