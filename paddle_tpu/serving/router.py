"""Fleet tier: a replica router over N serving engines.

ROADMAP direction 3's millions-of-users shape: one :class:`Router`
fronts N :class:`~paddle_tpu.serving.api.ServingEngine` replicas (each
with its own device plane / mesh slice, ideally sharing ONE obs
registry and tracer so the fleet scrapes as a single surface) and
routes every ``submit()`` on real signals:

  * **prefix affinity** — ``EngineCore.prefix_probe(prompt)`` reports
    each replica's longest radix-cached prefix WITHOUT admitting or
    pinning (a pure host walk); the router picks the replica with the
    longest hit, tie-broken by load, so shared-prefix traffic
    (system prompts, multi-turn history) keeps landing where its KV
    already lives and TTFT stays O(suffix) fleet-wide;
  * **health** — the PR-8 robustness surface is the routing input:
    replicas at ``quarantined``/``circuit_open`` are EXCLUDED,
    ``degraded`` replicas are deprioritized behind healthy ones, and a
    replica being drained (:meth:`Router.drain`) takes no new work
    while its in-flight requests finish;
  * **SLO-aware admission** — the fleet-level bounded queue
    (``max_queue`` across all replicas) and each engine's own
    submit-time backpressure (projected TTFT vs deadline, per-replica
    queue bound) gate admission; when every eligible replica rejects,
    the router re-raises :class:`RequestRejected` carrying the BEST
    replica's ``retry_after_s`` (always finite and clamped —
    serving/metrics.py).

**Failover, exactly once.**  A request that dies with a
replica-attributed terminal ``failed`` status (a quarantine casualty, a
poisoned decode row, a prefill fault) is transparently resubmitted ONCE
to the best healthy replica.  The fleet request id doubles as the
idempotency key: ``attempts`` caps total submissions at two, and the
``delivered`` high-water mark dedups the client-visible stream — the
retry regenerates tokens from position 0 (greedy / seeded-sampling
determinism makes the regenerated prefix identical), and the router
forwards only positions the client has not yet seen, so every token
position reaches the client exactly once.  Failures the CLIENT caused
(a raising stream callback) are never failed over.  ``cancel()``,
``result()``, ``stream()`` and ``purge()`` always resolve through the
router's authoritative fleet-id -> (replica, engine-id) map, so they
follow the request across a failover.

**Disaggregated prefill/decode roles.**  Each replica carries a role —
``prefill`` / ``decode`` / ``unified`` — and when the fleet holds
prefill replicas, long prompts take a TWO-PHASE path: the router
submits them to a prefill replica (prefix-affinity on the prefill
side) capped at ONE token, and when that prefill finishes, the
prompt's radix blocks move to the lightest-loaded decode replica
through the fault-tolerant KV handoff state machine
(serving/handoff.py: ``staged -> in_flight -> committed | aborted``,
riding the existing gather/scatter programs — zero new compiled
surface), where the request is resubmitted for its decode phase.  The
first token was already delivered from the prefill side, so TTFT never
waits on the transfer, and the ``delivered`` high-water mark dedups
the decode side's deterministic regeneration exactly like a failover
retry.  A handoff fault at any stage retries once, falls back to
RE-PREFILLING on the decode side, or fails the request terminally —
never leaking a block, slot, or radix pin on either replica (the
disagg chaos suite pins this per injection point).  Short prompts
(below ``prefill_threshold``) skip the prefill plane entirely.  An
attached :class:`~paddle_tpu.serving.autoscaler.Autoscaler` sizes the
decode side against ``router.queue_depth``, spawning behind a warmup
gate and retiring through :meth:`Router.drain` /
:meth:`Router.retire`.  See docs/serving.md "Disaggregated fleet".

The router is pure host-side control plane: it never touches a device
array and adds zero work to any engine's hot step loop.  Replicas
should be built with ``fault_tolerance=FaultToleranceConfig(...)`` —
the watchdog's containment is what turns a replica fault into the
terminal ``failed`` status the failover scan routes on; without it a
step exception propagates out of :meth:`Router.step` to the caller.

Fleet accounting (chaos invariant) lives in ``serving/fleet.py``;
``scripts/fleet_chaos_smoke.py`` drives one injected replica fault
end-to-end and ``tests/test_zz_fleet_serving.py`` +
``tests/test_zz_disagg_serving.py`` pin the invariant.
See docs/serving.md "Fleet tier".
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .api import RequestOutput, ServingEngine
from .errors import EngineStalledError, RequestRejected
from .handoff import ABORTED, HandoffManager
from .health import CIRCUIT_OPEN, DEGRADED, QUARANTINED
from .scheduler import PRIORITIES, SamplingParams

__all__ = ["Router", "ReplicaHandle", "ROLES"]

# the routing roles a replica may carry (docs/serving.md
# "Disaggregated fleet"): prefill replicas take only the router's
# prefill-stage submissions, decode replicas take decode-stage work,
# unified replicas take both (the single-role fleet default)
ROLES = ("prefill", "decode", "unified")

# terminal reasons a failover must never retry: the failure is
# attributed to the CLIENT's sink, not the replica — a resubmission
# would re-raise into the same callback and burn the retry for nothing
_CLIENT_FAULT_PREFIX = "stream callback"


class ReplicaHandle:
    """Router-side view of one replica: the engine plus the routing
    state the router owns about it (role, drain/retire flags, routed
    count, and the step-latency EWMA the straggler detector reads)."""

    __slots__ = ("index", "engine", "role", "draining", "retired",
                 "killed", "routed", "step_ewma_s", "slow_ticks",
                 "_slow_streak", "_fast_streak", "_observed")

    # EWMA smoothing for the router-measured per-replica step wall time
    # (the straggler detector's input): ~10-step memory — fast enough
    # to catch a real straggler, slow enough that hysteresis, not the
    # average, decides flapping
    STEP_EWMA_ALPHA = 0.2

    def __init__(self, index: int, engine: ServingEngine,
                 role: str = "unified"):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.index = index
        self.engine = engine
        self.role = role
        self.draining = False
        # straggler-detection state (docs/serving.md "Tail latency"):
        # the router times each replica's step() itself, so an
        # engine-internal stall (slow_step chaos, a real slow device)
        # and a router-level one (replica_slow chaos) both register;
        # slow_ticks counts consecutive fleet steps spent marked slow
        # (the autoscaler's replace-persistently-slow input)
        self.step_ewma_s = 0.0
        self.slow_ticks = 0
        self._slow_streak = 0
        self._fast_streak = 0
        self._observed = True      # had a BUSY step this fleet step
        # retired replicas keep their handle (indices stay stable in
        # the fleet-id map) but their engine is closed and they never
        # re-enter rotation — the autoscaler's drain-based retirement
        self.retired = False
        # killed replicas ALSO set retired (they left the fleet) but
        # their engine was never drained or closed — Router.kill's
        # simulated SIGKILL; fleet accounting skips their baselines
        # (a dead process returns nothing) and the autoscaler's
        # resurrection path spawns their replacement
        self.killed = False
        self.routed = 0          # fleet requests ever routed here

    def observe_step(self, seconds: float) -> None:
        """Fold one router-measured step wall time into the EWMA."""
        a = self.STEP_EWMA_ALPHA
        self.step_ewma_s = seconds if self.step_ewma_s == 0.0 \
            else (1 - a) * self.step_ewma_s + a * seconds

    @property
    def load(self) -> int:
        """Queued + placed requests — the affinity tie-breaker."""
        core = self.engine.core
        return core.scheduler.queue_depth + core.scheduler.active

    @property
    def health_rank(self) -> int:
        """The route-order deprioritization band (docs/serving.md "Tail
        latency" routing matrix): 0 healthy, 1 slow, 2 degraded,
        3 slow+degraded — healthy beats slow beats degraded among the
        ROUTABLE replicas (excluded states never reach the sort)."""
        h = self.engine.health
        return (2 if h.state == DEGRADED else 0) + (1 if h.slow else 0)

    def serves(self, stage: str) -> bool:
        """May this replica take new ``stage`` ("prefill"/"decode")
        work?  Role compatibility only — health/drain gates live in
        ``Router._eligible``."""
        if stage == "prefill":
            return self.role == "prefill"
        return self.role in ("decode", "unified")

    def __repr__(self) -> str:
        return (f"ReplicaHandle({self.index}, role={self.role!r}, "
                f"health={self.engine.health.state!r}, "
                f"draining={self.draining}, retired={self.retired}, "
                f"killed={self.killed}, load={self.load})")


class _FleetRequest:
    """One client-visible request's routing record.  ``fleet_id`` is
    the idempotency key: ``attempts`` caps submissions at two (original
    + one failover) and ``delivered`` is the exactly-once high-water
    mark for the client stream."""

    __slots__ = ("fleet_id", "prompt", "max_new_tokens", "sampling",
                 "eos_token_id", "client_stream", "deadline_s",
                 "ttft_deadline_s", "submit_time", "replica",
                 "engine_rid", "attempts", "delivered", "history",
                 "role_stage", "handoffs", "override", "priority",
                 "hedge_replica", "hedge_rid", "hedged",
                 "journal_hwm", "journaled_submit", "journaled_terminal")

    def __init__(self, fleet_id: int, prompt: np.ndarray,
                 max_new_tokens: int, sampling, eos_token_id,
                 client_stream, deadline_s, ttft_deadline_s,
                 priority: str = "interactive"):
        self.fleet_id = fleet_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.eos_token_id = eos_token_id
        self.client_stream = client_stream
        self.deadline_s = deadline_s
        self.ttft_deadline_s = ttft_deadline_s
        self.submit_time = 0.0        # perf_counter at FIRST submission
        self.replica = -1             # current owner (authoritative)
        self.engine_rid = -1
        self.attempts = 0
        self.delivered = 0            # client-visible token positions
        # (replica, engine_rid, status_reason) per surrendered attempt
        self.history: List[Tuple[int, int, str]] = []
        # disaggregated-fleet routing phase: "prefill" while the request
        # runs (one-token-capped) on a prefill replica, "decode" once it
        # owns a full submission on a decode/unified replica
        self.role_stage = "decode"
        self.handoffs = 0             # committed/aborted migrations
        self.priority = priority      # "interactive" | "batch"
        # hedged-request state (docs/serving.md "Tail latency"): while
        # a hedge is live the request runs on TWO replicas — replica/
        # engine_rid is the primary attempt, hedge_replica/hedge_rid
        # the duplicate; first finished wins and the loser is purged.
        # ``hedged`` stays True after resolution: ONE hedge per fleet
        # id, ever (it consumed the attempts<=2 budget)
        self.hedge_replica = -1
        self.hedge_rid = -1
        self.hedged = False
        # router-level terminal stamp: set only when the handoff
        # machinery exhausts every placement (the engine-side record is
        # then a stale 1-token "finished" view); result() applies it
        self.override: Optional[Tuple[str, str]] = None
        # durable-journal bookkeeping (docs/serving.md "Crash
        # recovery"): the last delivered mark journaled, and the
        # exactly-once guards for the submit/terminal records
        self.journal_hwm = 0
        self.journaled_submit = False
        self.journaled_terminal = False


class _RouterMetrics:
    """The router's obs instruments, bound get-or-create into the
    (usually shared) registry — glossary rows in docs/observability.md."""

    def __init__(self, registry, tracer):
        self.registry = registry
        self.tracer = tracer
        self.lane = tracer.claim_lane_block()
        tracer.set_lane_name(self.lane, "serving.router", pin=True)
        g, c = registry.gauge, registry.counter
        self.g_replicas = g("router.replicas",
                            "replicas fronted by this router")
        self.g_healthy = g("router.healthy_replicas",
                           "replicas currently routable (healthy or "
                           "degraded, not draining)")
        self.g_draining = g("router.draining_replicas",
                            "replicas draining (no new admissions)")
        self.g_queue = g("router.queue_depth",
                         "fleet-wide waiting requests at the last step")
        self.c_routed = c("router.requests_routed",
                          "fleet submissions accepted and routed")
        self.c_hit_tokens = c("router.prefix_hit_tokens",
                              "prompt tokens the routed replica's radix "
                              "cache already held at routing time")
        self.c_failovers = c("router.failovers",
                             "requests resubmitted to a healthy replica "
                             "after a replica-attributed failure")
        self.c_failover_exhausted = c(
            "router.failovers_exhausted",
            "replica-attributed failures that could NOT fail over "
            "(retry spent, deadline blown, or no replica accepted)")
        self.c_rejected = c("router.requests_rejected",
                            "fleet submissions refused (no healthy "
                            "replica / fleet queue / every replica "
                            "rejected)")
        # disaggregated-fleet surface (docs/serving.md "Disaggregated
        # fleet"; glossary rows in docs/observability.md)
        self.g_prefill = g("router.role_prefill_replicas",
                           "prefill-role replicas in rotation")
        self.g_decode = g("router.role_decode_replicas",
                          "decode-capable (decode/unified) replicas in "
                          "rotation")
        self.g_retired = g("router.retired_replicas",
                           "replicas retired out of the fleet (drained, "
                           "closed, indices kept stable)")
        self.c_handoff_staged = c("handoff.staged",
                                  "KV handoffs opened (prefill-side "
                                  "path pinned)")
        self.c_handoff_committed = c("handoff.committed",
                                     "KV handoffs whose blocks landed "
                                     "on the decode replica")
        self.c_handoff_aborted = c("handoff.aborted",
                                   "KV handoffs aborted (the request "
                                   "re-prefilled on the decode side or "
                                   "failed terminally)")
        self.c_handoff_retries = c("handoff.retries",
                                   "transfer attempts retried after an "
                                   "in-flight fault")
        self.c_handoff_blocks = c("handoff.blocks_moved",
                                  "radix blocks moved prefill -> decode")
        self.c_handoff_failed = c("handoff.failed_terminal",
                                  "requests failed terminally because "
                                  "no decode replica could place the "
                                  "post-handoff submission")
        # crash-consistency surface (docs/serving.md "Crash recovery";
        # glossary rows in docs/observability.md)
        self.g_killed = g("router.killed_replicas",
                          "replicas SIGKILLed out of the fleet "
                          "(no drain, no close)")
        self.c_crash_reattributed = c(
            "router.crash_reattributed",
            "in-flight requests re-attributed through the failover "
            "path after their replica was killed")
        self.c_replay_resubmitted = c(
            "router.replay_resubmitted",
            "journaled non-terminal requests resubmitted by "
            "Router.recover")
        self.c_replay_expired = c(
            "router.replay_expired",
            "journaled requests whose deadline was spent across the "
            "downtime — settled deadline_exceeded without resubmit")
        # tail-latency surface (docs/serving.md "Tail latency";
        # glossary rows in docs/observability.md)
        self.g_slow = g("router.slow_replicas",
                        "replicas currently marked slow by the "
                        "straggler detector (deprioritized, not "
                        "excluded)")
        self.g_brownout = g("router.brownout_level",
                            "overload-shedding ladder level (0 normal, "
                            "1 shed batch + suspend hedging, 2 "
                            "tightened admission)")
        self.c_hedges = c("router.hedges",
                          "duplicate submissions issued for "
                          "deadline-at-risk requests (one per fleet "
                          "id, ever)")
        self.c_hedge_wins = c("router.hedge_wins",
                              "hedges that finished before their "
                              "primary attempt (the primary was purged)")
        self.c_hedge_failed = c("router.hedges_failed",
                                "hedge submissions that failed closed "
                                "(every target rejected, or the "
                                "hedge_submit chaos point fired)")
        self.c_shed_batch = c("router.shed_batch",
                              "batch-class submissions shed by the "
                              "brownout ladder (rejected with an "
                              "honest retry_after_s)")

    def on_slow(self, phase: str, replica: int, **attrs) -> None:
        """``straggler_*`` lifecycle event on the router lane (mark /
        clear)."""
        self.tracer.event(f"straggler_{phase}", lane=self.lane,
                          replica=replica, **attrs)

    def on_hedge(self, phase: str, fleet_id: int, **attrs) -> None:
        """``hedge_*`` lifecycle event on the router lane (issue / win /
        purge / failed); the matching counters are bumped at the
        transition sites."""
        self.tracer.event(f"hedge_{phase}", lane=self.lane,
                          fleet_id=fleet_id, **attrs)

    def on_brownout(self, phase: str, level: int, **attrs) -> None:
        """``brownout_*`` lifecycle event (enter / exit / shed) plus the
        ladder gauge."""
        self.g_brownout.set(level)
        self.tracer.event(f"brownout_{phase}", lane=self.lane,
                          level=level, **attrs)

    def on_crash(self, phase: str, replica: int, **attrs) -> None:
        """``crash_*`` lifecycle event on the router lane (kill,
        re-attribution, resurrection)."""
        self.tracer.event(f"crash_{phase}", lane=self.lane,
                          replica=replica, **attrs)

    def on_replay(self, phase: str, **attrs) -> None:
        """``replay_*`` lifecycle event on the router lane (begin,
        resubmit, expired, unplaced, done)."""
        self.tracer.event(f"replay_{phase}", lane=self.lane, **attrs)

    def on_handoff(self, phase: str, fleet_id: int, src: int, dst: int,
                   **attrs) -> None:
        """Discrete handoff lifecycle event on the router lane; the
        matching counters are bumped by the router at the transition
        sites."""
        self.tracer.event(f"handoff_{phase}", lane=self.lane,
                          fleet_id=fleet_id, src=src, dst=dst, **attrs)

    def on_route(self, fleet_id: int, replica: int, hit_tokens: int) -> None:
        self.c_routed.inc()
        if hit_tokens > 0:
            self.c_hit_tokens.inc(hit_tokens)

    def on_failover(self, fleet_id: int, src: int, dst: int,
                    reason: str) -> None:
        self.c_failovers.inc()
        self.tracer.event("failover", lane=self.lane, fleet_id=fleet_id,
                          from_replica=src, to_replica=dst,
                          reason=str(reason)[:200])

    def on_failover_exhausted(self, fleet_id: int, replica: int,
                              why: str) -> None:
        self.c_failover_exhausted.inc()
        self.tracer.event("failover_exhausted", lane=self.lane,
                          fleet_id=fleet_id, replica=replica,
                          reason=str(why)[:200])

    def on_reject(self, reason: str) -> None:
        self.c_rejected.inc()
        self.tracer.event("router_reject", lane=self.lane, reason=reason)

    def on_drain(self, replica: int, phase: str) -> None:
        self.tracer.event(phase, lane=self.lane, replica=replica)

    def publish(self, handles: Sequence[ReplicaHandle]) -> None:
        self.g_replicas.set(len(handles))
        live = [h for h in handles if not h.retired]
        healthy = sum(1 for h in live if not h.draining
                      and h.engine.health.routable)
        self.g_healthy.set(healthy)
        self.g_draining.set(sum(1 for h in live if h.draining))
        self.g_queue.set(sum(h.engine.core.scheduler.queue_depth
                             for h in live))
        self.g_prefill.set(sum(1 for h in live if not h.draining
                               and h.role == "prefill"))
        self.g_decode.set(sum(1 for h in live if not h.draining
                              and h.role in ("decode", "unified")))
        self.g_retired.set(sum(1 for h in handles if h.retired))
        self.g_killed.set(sum(1 for h in handles if h.killed))
        self.g_slow.set(sum(1 for h in live if h.engine.health.slow))


class _Brownout:
    """The overload-shedding ladder (docs/serving.md "Tail latency"):
    a host-side hysteretic controller over the fleet queue depth — the
    same signal the SLO rejection reads — escalating one level per
    sustained breach and de-escalating one level per sustained
    recovery (the autoscaler's consecutive-tick idiom):

      * level 0 — normal service;
      * level 1 — shed: new BATCH-class submissions reject with an
        honest ``retry_after_s`` and hedging is suspended (duplicates
        are load an overloaded fleet must not amplify);
      * level 2 — tightened admission: while the queue still exceeds
        the ENTER depth, interactive submissions reject too — honest
        fast failure beats a deadline the fleet already knows it will
        blow.

    Armed only when ``depth`` (the level-1 enter bound; level 2 enters
    at twice it) is given; exit thresholds sit at half the entry
    thresholds so the ladder cannot chatter on a boundary queue."""

    __slots__ = ("depth", "hysteresis", "level", "_above", "_below")

    def __init__(self, depth: Optional[int], hysteresis: int):
        if depth is not None and depth < 1:
            raise ValueError("brownout_depth must be >= 1 (or None)")
        if hysteresis < 1:
            raise ValueError("brownout_hysteresis must be >= 1")
        self.depth = depth
        self.hysteresis = hysteresis
        self.level = 0
        self._above = 0
        self._below = 0

    def _enter_depth(self, level: int) -> int:
        return self.depth * (2 ** (level - 1))

    def update(self, queue_depth: int,
               exit_only: bool = False) -> Optional[str]:
        """One control tick; returns "enter"/"exit" on a level
        transition (None otherwise).  ``exit_only`` marks a
        SUBMIT-time observation: it may walk the ladder DOWN (the
        idle-fleet exit path — rejections enqueue nothing, so step()
        may never run again) but never up, or a burst of submissions
        would escalate faster than the per-step hysteresis the
        thresholds are calibrated for."""
        if self.depth is None:
            return None
        if self.level < 2 and queue_depth >= self._enter_depth(
                self.level + 1):
            if exit_only:
                self._below = 0      # deep queue: no exit progress
                return None
            self._above += 1
            self._below = 0
            if self._above >= self.hysteresis:
                self.level += 1
                self._above = 0
                return "enter"
            return None
        self._above = 0
        if self.level > 0 and queue_depth <= \
                self._enter_depth(self.level) // 2:
            self._below += 1
            if self._below >= self.hysteresis:
                self.level -= 1
                self._below = 0
                return "exit"
        else:
            self._below = 0
        return None


class Router:
    """Prefix-affinity, health-aware request router over N serving
    replicas — the fleet tier (docs/serving.md "Fleet tier").

    ``replicas`` are pre-built :class:`ServingEngine` instances (build
    them onto ONE shared registry/tracer for a single scrape surface —
    :meth:`Router.build` does exactly that).  The router owns the
    fleet-id namespace: every id handed out by :meth:`submit` resolves
    through the authoritative request -> replica map, across failovers.

    ``max_queue`` bounds the FLEET queue (sum of replica queue depths);
    per-replica bounds/SLO checks still apply at each engine.
    ``failover=False`` disables resubmission (replica failures surface
    as terminal ``failed``); ``affinity=False`` degrades routing to
    round-robin over the eligible replicas — the measured baseline the
    prefix-affinity win is pinned against.

    ``roles`` assigns each replica its fleet role (default: the
    engine's own ``role`` attribute, ``unified`` when absent).  A fleet
    holding ``prefill`` replicas is DISAGGREGATED: prompts of at least
    ``prefill_threshold`` tokens (needing more than one output token)
    run their prefill on a prefill replica and migrate to a decode
    replica through the KV handoff (serving/handoff.py); shorter
    prompts route straight to decode/unified replicas.  The threshold
    is REQUIRED when prefill roles are present — every request pays
    the two-phase migration above it, so the split point is a sizing
    decision the operator must make (an explicit 0 routes everything
    through the prefill plane).  ``faults`` arms the router-level
    chaos points (``handoff_*``, ``replica_crash``) — None in
    production.

    **Tail-latency defense** (docs/serving.md "Tail latency"):
    ``slow_threshold``/``slow_hysteresis`` parameterize the straggler
    detector — a replica whose router-measured step-latency EWMA
    exceeds the fleet median by the threshold factor for the
    hysteresis's consecutive fleet steps is marked ``slow``
    (``EngineHealth.slow``) and deprioritized by the route order
    between healthy and degraded; it recovers through the same
    hysteresis.  ``hedging`` (default on) arms hedged requests: a
    deadline-carrying request whose projected completion on its
    current replica breaches the deadline gets ONE duplicate
    submission on the best OTHER healthy replica after a p95-based
    delay — first to finish wins, the loser is purged, and the
    fleet-id idempotency key + delivered high-water mark keep the
    client stream exactly-once and token-identical.  ``brownout_depth``
    arms the overload-shedding ladder (None = off): sustained fleet
    queue depth at the bound sheds BATCH-priority work first (honest
    ``retry_after_s``), suspends hedging, and at twice the bound
    tightens admission for everyone; it exits with hysteresis.

    ``journal`` attaches a durable request :class:`~paddle_tpu.serving.
    journal.Journal` (docs/serving.md "Crash recovery"): every accepted
    submit, the per-step delivered high-water marks, and every terminal
    disposition are journaled with FLEET ids, off every engine's hot
    path (``if journal is None`` — the faults pattern, zero overhead
    when unset).  After a process crash, build a fresh fleet on the
    reopened journal and call :meth:`recover` — non-terminal requests
    resubmit and the journaled high-water mark dedups their
    deterministic regeneration, so clients see each recorded token
    position at most once.  Replicas behind a journaled router should
    be built journal-LESS (the router's fleet-id records are the
    authoritative ledger).
    """

    def __init__(self, replicas: Sequence[ServingEngine], *,
                 max_queue: Optional[int] = None,
                 failover: bool = True,
                 affinity: bool = True,
                 roles: Optional[Sequence[str]] = None,
                 prefill_threshold: Optional[int] = None,
                 faults=None,
                 journal=None,
                 hedging: bool = True,
                 slow_threshold: float = 3.0,
                 slow_hysteresis: int = 3,
                 brownout_depth: Optional[int] = None,
                 brownout_hysteresis: int = 4,
                 registry=None, tracer=None):
        if not replicas:
            raise ValueError("Router needs at least one replica engine")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        if slow_threshold <= 1.0:
            raise ValueError(
                "slow_threshold must exceed 1.0 — a replica at the "
                "fleet median must never be an outlier")
        if slow_hysteresis < 1:
            raise ValueError("slow_hysteresis must be >= 1")
        if prefill_threshold is not None and prefill_threshold < 0:
            raise ValueError("prefill_threshold must be >= 0 (or None)")
        if roles is None:
            roles = [getattr(eng, "role", "unified") for eng in replicas]
        if len(roles) != len(replicas):
            raise ValueError(
                f"roles has {len(roles)} entries for {len(replicas)} "
                f"replicas")
        self._handles = [ReplicaHandle(i, eng, role=r)
                         for i, (eng, r) in enumerate(zip(replicas,
                                                          roles))]
        if any(h.role == "prefill" for h in self._handles):
            if not any(h.serves("decode") for h in self._handles):
                raise ValueError(
                    "a disaggregated fleet needs at least one decode "
                    "or unified replica — prefill replicas never "
                    "decode past the first token")
            if prefill_threshold is None:
                raise ValueError(
                    "a fleet with prefill-role replicas requires an "
                    "explicit prefill_threshold (prompt length in "
                    "tokens at which requests take the two-phase "
                    "prefill->handoff path; 0 routes every multi-token "
                    "request through the prefill plane)")
        self.max_queue = max_queue
        self.failover = failover
        self.affinity = affinity
        self.prefill_threshold = prefill_threshold
        self.faults = faults
        self.hedging = hedging
        self.slow_threshold = slow_threshold
        self.slow_hysteresis = slow_hysteresis
        self._brownout = _Brownout(brownout_depth, brownout_hysteresis)
        self.registry = registry if registry is not None \
            else replicas[0].registry
        self.tracer = tracer if tracer is not None \
            else replicas[0].tracer
        self.metrics = _RouterMetrics(self.registry, self.tracer)
        self._handoffs = HandoffManager(faults=faults)
        self._autoscaler = None       # attach via Autoscaler(router, ...)
        self._requests: Dict[int, _FleetRequest] = {}
        self._live: set = set()       # fleet ids the failover scan owns
        self.journal = journal
        if journal is not None:
            journal.bind_metrics(self.registry)
            # the fleet-id namespace must never reuse a journaled id —
            # a reused id would collide two requests in the ledger
            start = max(journal.state) + 1 if journal.state else 0
            self._ids = itertools.count(start)
        else:
            self._ids = itertools.count()
        self._rr = 0                  # round-robin cursor (affinity off)
        self._closed = False
        self.metrics.publish(self._handles)

    @classmethod
    def build(cls, model_factory: Callable, replicas: int = 2, *,
              registry=None, tracer=None, max_queue: Optional[int] = None,
              failover: bool = True, affinity: bool = True,
              roles: Optional[Sequence[str]] = None,
              prefill_threshold: Optional[int] = None,
              faults=None,
              hedging: bool = True,
              slow_threshold: float = 3.0,
              slow_hysteresis: int = 3,
              brownout_depth: Optional[int] = None,
              brownout_hysteresis: int = 4,
              prefill_engine_kw: Optional[dict] = None,
              decode_engine_kw: Optional[dict] = None,
              aot_store=None,
              **engine_kw) -> "Router":
        """Construct ``replicas`` engines onto ONE shared registry and
        tracer (fresh ones when not given) and front them with a router.
        ``model_factory()`` is called once per replica — return the same
        weights (e.g. re-seed inside the factory) when fleet-wide token
        parity matters; ``engine_kw`` is forwarded to every
        :class:`ServingEngine`.  With ``roles`` given, per-role kwargs
        override the shared ones — e.g. ``prefill_engine_kw=dict(
        num_slots=2, max_prefill_tokens_per_step=None)`` for the
        big-bucket prefill shape, ``decode_engine_kw=dict(num_slots=16)``
        for the all-slots decode shape.

        ``aot_store`` is the fleet's shared zero-cold-start program
        store (serving/aot.py): every replica warm-loads its compiled
        programs from the one store instead of tracing at construction.
        Per-role kwarg overrides that change the engine's compile
        fingerprint (slot count, bucket shape) fall back to tracing for
        that role — loudly, via ``aot_miss`` — rather than refusing to
        build."""
        from ..obs import MetricsRegistry, Tracer
        registry = registry if registry is not None else MetricsRegistry()
        tracer = tracer if tracer is not None else Tracer()
        role_list = list(roles) if roles is not None \
            else ["unified"] * replicas
        if len(role_list) != replicas:
            raise ValueError(
                f"roles has {len(role_list)} entries for {replicas} "
                f"replicas")
        engines = []
        for r in role_list:
            kw = dict(engine_kw)
            if aot_store is not None:
                kw.setdefault("aot_store", aot_store)
            if r == "prefill" and prefill_engine_kw:
                kw.update(prefill_engine_kw)
            elif r == "decode" and decode_engine_kw:
                kw.update(decode_engine_kw)
            engines.append(ServingEngine(model_factory(),
                                         registry=registry,
                                         tracer=tracer, role=r, **kw))
        return cls(engines, max_queue=max_queue, failover=failover,
                   affinity=affinity, roles=role_list,
                   prefill_threshold=prefill_threshold, faults=faults,
                   hedging=hedging, slow_threshold=slow_threshold,
                   slow_hysteresis=slow_hysteresis,
                   brownout_depth=brownout_depth,
                   brownout_hysteresis=brownout_hysteresis,
                   registry=registry, tracer=tracer)

    # ---------------------------------------------------------- topology
    @property
    def replicas(self) -> Tuple[ReplicaHandle, ...]:
        return tuple(self._handles)

    @property
    def disaggregated(self) -> bool:
        """True once the fleet holds a live prefill-role replica."""
        return any(h.role == "prefill" and not h.retired
                   for h in self._handles)

    @property
    def queue_depth(self) -> int:
        """Fleet-wide waiting requests (the ``max_queue`` bound)."""
        return sum(h.engine.core.scheduler.queue_depth
                   for h in self._handles if not h.retired)

    @property
    def in_flight(self) -> int:
        """Queued + placed requests across the fleet."""
        return sum(h.load for h in self._handles if not h.retired)

    @property
    def routable_count(self) -> int:
        """Replicas that could take new decode-capable work right now
        (role-compatible, in rotation, health routable) — the headline
        number of the fail-fast snapshot ``run_until_complete`` raises
        when the fleet is dead."""
        return len(self._eligible("decode"))

    @property
    def fleet_dead(self) -> bool:
        """True when NO replica can ever make progress again: every
        handle is retired/killed or its circuit is open (a terminal
        state — step() is a no-op there).  Draining and quarantined
        replicas do NOT count as dead: a draining replica still
        finishes its in-flight work and a quarantined one is
        mid-rebuild.  ``run_until_complete`` fails fast on this instead
        of spinning ``stall_steps`` idle iterations into the generic
        no-progress stall."""
        return all(h.retired or h.engine.health.circuit_open
                   for h in self._handles)

    def _handle(self, replica: int) -> ReplicaHandle:
        if not 0 <= replica < len(self._handles):
            raise KeyError(
                f"unknown replica index {replica} — this router fronts "
                f"{len(self._handles)} replicas")
        return self._handles[replica]

    def add_replica(self, engine: ServingEngine,
                    role: str = "decode") -> int:
        """Append one fully-built replica to the rotation (the
        autoscaler's spawn endpoint — the engine must be READY: a
        half-built replica must never reach this call).  Returns its
        replica index; indices are append-only and never reused, so
        the fleet-id map stays stable across topology changes."""
        h = ReplicaHandle(len(self._handles), engine, role=role)
        self._handles.append(h)
        self.metrics.publish(self._handles)
        return h.index

    def retire(self, replica: int) -> None:
        """Permanently remove a DRAINED replica from the fleet: close
        its engine and mark the handle retired (kept in place — indices
        stay stable; completed requests still resolve through it).
        The graceful path is ``drain(i)`` → wait ``drained(i)`` →
        ``retire(i)`` — the autoscaler's scale-down does exactly this.
        Raises when the replica still has work (retiring it would
        strand in-flight requests) or was already retired."""
        h = self._handle(replica)
        if h.retired:
            raise ValueError(f"replica {replica} is already retired")
        if h.engine.core.scheduler.has_work():
            raise ValueError(
                f"replica {replica} still has queued or in-flight work "
                f"— drain it and wait for drained() first")
        h.retired = True
        h.engine.close()
        self.metrics.on_drain(replica, "retire")
        self.metrics.publish(self._handles)

    # ------------------------------------------------------------ crash
    def kill(self, replica: int) -> int:
        """Simulated SIGKILL of one replica: it vanishes from the fleet
        WITHOUT drain or close — no in-flight request finishes, no
        queue drains, no telemetry detaches (a dead process runs no
        cleanup).  Every live fleet request it owned is re-attributed
        on the spot through the existing failover path (same attempts
        budget, same deadline shrinking, same delivered-high-water-mark
        dedup); requests that cannot fail over settle terminally at the
        router.  Pending KV handoffs touching the replica abort (their
        source pins are host objects the manager still holds).  The
        handle stays in place killed+retired — indices stay stable —
        and the autoscaler's resurrection path spawns a replacement
        through its normal warmup gate.  Returns the number of
        re-attributed (resubmitted) requests."""
        h = self._handle(replica)
        if h.retired:
            raise ValueError(
                f"replica {replica} already left the fleet "
                f"(retired/killed) — there is nothing to kill")
        h.killed = True
        h.retired = True            # out of rotation; engine NOT closed
        # a stale direct reference to the dead engine must fail fast,
        # not serve: the health machine pins it terminally dead
        h.engine.health.mark_dead("killed (simulated SIGKILL)")
        self.metrics.on_crash("kill", replica,
                              live_requests=sum(
                                  1 for fid in self._live
                                  if self._requests[fid].replica
                                  == replica))
        # abort handoffs whose source or destination just died — the
        # pin release is a host-side operation on objects the manager
        # holds, so it is safe against the dead engine
        for fid in list(self._handoffs.records):
            rec = self._handoffs.records.get(fid)
            if rec is not None and replica in (rec.src, rec.dst):
                self._handoffs.abort(rec, f"replica {replica} killed "
                                          f"mid-handoff")
                self._abort_metrics(rec)
        reattributed = 0
        for fid in sorted(self._live):
            fr = self._requests[fid]
            if fr.hedge_rid >= 0 and fr.hedge_replica == replica:
                # the hedge died with the replica (dead process —
                # nothing to purge there); the primary stands alone
                self.purge_hedge(fr, f"replica {replica} killed "
                                     f"mid-hedge")
            if fr.replica != replica:
                continue
            if fr.hedge_rid >= 0:
                # the PRIMARY died but its hedge is already running on
                # a live replica: promote the hedge instead of burning
                # a reattribution the attempts budget no longer has
                self.resolve_hedge(fr, f"replica {replica} killed "
                                       f"(simulated SIGKILL) — hedge "
                                       f"survives")
                continue
            if self._reattribute(fr, f"replica {replica} killed "
                                     f"(simulated SIGKILL)"):
                reattributed += 1
        self.metrics.publish(self._handles)
        return reattributed

    def _reattribute(self, fr: _FleetRequest, reason: str) -> bool:
        """Move one fleet request off a DEAD replica: the failover path
        without an engine record to read (the dead replica's state is
        gone by definition).  Returns True when a live replica accepted
        the resubmission; False settles the request terminally at the
        router (deadline spent, attempts exhausted, or no replica
        accepted)."""
        now = time.perf_counter()
        dead = fr.replica

        def settle(status: str, why: str) -> bool:
            self.metrics.on_failover_exhausted(fr.fleet_id, dead, why)
            fr.override = (status, why)
            self._journal_terminal(fr, status, why)
            self._live.discard(fr.fleet_id)
            return False

        if fr.deadline_s is not None \
                and now - fr.submit_time >= fr.deadline_s:
            return settle("deadline_exceeded",
                          f"deadline spent when {reason}")
        if fr.attempts >= 2:
            return settle("failed",
                          f"{reason}; failover budget already spent")
        for h, hit in self._route_order(self._eligible("decode"),
                                        fr.prompt):
            try:
                rid = self._submit_to(h, fr, now=now)
            except RequestRejected:
                continue
            fr.history.append((dead, fr.engine_rid, reason))
            fr.replica, fr.engine_rid = h.index, rid
            fr.role_stage = "decode"
            fr.attempts += 1
            h.routed += 1
            self.metrics.c_crash_reattributed.inc()
            self.metrics.on_failover(fr.fleet_id, dead, h.index, reason)
            return True
        return settle("failed", f"{reason}; no live replica accepted "
                                f"the re-attribution")

    def recover(self, journal=None, *,
                stream_factory: Optional[Callable] = None) -> Dict:
        """Replay a reopened journal into this (fresh) fleet — the
        restart half of crash consistency (docs/serving.md "Crash
        recovery").  For every journaled submit with no terminal
        record:

          * the deadline budget is re-checked against WALL-CLOCK
            downtime (the submit record carries ``time.time()``); a
            request whose budget was spent while the process was dead
            settles ``deadline_exceeded`` in the journal WITHOUT a
            resubmission;
          * everything else resubmits in full with the remaining
            budget, and the journaled delivered high-water mark seeds
            the exactly-once dedup — the deterministic regeneration
            (same prompt, same seed, same greedy/sampling spec) re-runs
            from position 0 but the client stream only sees positions
            the dead incarnation had not recorded;
          * recovered requests route decode-direct (no prefill-stage
            shortcut — the failover rule: decode/unified replicas
            prefill fine), and a resubmission every replica refuses
            settles terminal ``failed``.

        ``stream_factory(fleet_id)``, when given, builds the client
        stream callback for each recovered request (the old process's
        callbacks died with it).  Returns a summary dict
        (``resubmitted`` / ``expired`` / ``unplaced`` counts).  Must
        run before any new traffic — a recovered fleet id joining a
        half-filled request map would alias."""
        if journal is not None:
            if self.journal is not None and self.journal is not journal:
                raise ValueError(
                    "router already has a different journal attached")
            self.journal = journal
            journal.bind_metrics(self.registry)
        if self.journal is None:
            raise ValueError(
                "recover() needs a journal — attach one at construction "
                "(Router(journal=...)) or pass it here")
        if self._requests:
            raise RuntimeError(
                "recover() must run on a fresh router, before any "
                "submit — recovered fleet ids would alias live ones")
        replayable = self.journal.replay()
        start = max(self.journal.state) + 1 if self.journal.state else 0
        self._ids = itertools.count(start)
        self.metrics.on_replay("begin", requests=len(replayable))
        now_wall = time.time()
        summary = {"resubmitted": 0, "expired": 0, "unplaced": 0}
        for fid in sorted(replayable):
            info = replayable[fid]
            rec, delivered = info["record"], info["delivered"]
            prompt = np.asarray(rec["prompt"], np.int32)
            sampling = None if rec.get("sampling") is None \
                else SamplingParams(**rec["sampling"])
            fr = _FleetRequest(fid, prompt, rec["max_new_tokens"],
                               sampling, rec.get("eos_token_id"),
                               None if stream_factory is None
                               else stream_factory(fid),
                               rec.get("deadline_s"),
                               rec.get("ttft_deadline_s"),
                               priority=rec.get("priority",
                                                "interactive"))
            fr.journaled_submit = True     # this IS the journaled submit
            fr.delivered = fr.journal_hwm = delivered
            fr.submit_time = time.perf_counter()
            # charge the downtime against the budgets: elapsed wall
            # clock since the original submission, deadlines relative
            elapsed = max(now_wall - rec.get("wall_time", now_wall), 0.0)
            expired = None
            if fr.deadline_s is not None:
                fr.deadline_s -= elapsed
                if fr.deadline_s <= 0:
                    expired = (f"end-to-end deadline "
                               f"{rec['deadline_s']}s spent across "
                               f"{elapsed:.3f}s including downtime")
            if fr.ttft_deadline_s is not None:
                if delivered > 0:
                    fr.ttft_deadline_s = None    # TTFT already met
                else:
                    fr.ttft_deadline_s -= elapsed
                    if expired is None and fr.ttft_deadline_s <= 0:
                        expired = (f"TTFT deadline "
                                   f"{rec['ttft_deadline_s']}s spent "
                                   f"across {elapsed:.3f}s including "
                                   f"downtime")
            if expired is not None:
                fr.override = ("deadline_exceeded", expired)
                self._journal_terminal(fr, *fr.override)
                self._requests[fid] = fr
                self.metrics.c_replay_expired.inc()
                self.metrics.on_replay("expired", fleet_id=fid,
                                       downtime_s=round(elapsed, 3))
                summary["expired"] += 1
                continue
            placed = False
            for h, hit in self._route_order(self._eligible("decode"),
                                            prompt):
                try:
                    rid = self._submit_to(h, fr)
                except RequestRejected:
                    continue
                self._place(fr, h, rid, hit)
                placed = True
                break
            if placed:
                self.metrics.c_replay_resubmitted.inc()
                self.metrics.on_replay("resubmit", fleet_id=fid,
                                       replica=fr.replica,
                                       delivered=delivered)
                summary["resubmitted"] += 1
            else:
                fr.override = ("failed", "no replica accepted the "
                                         "recovered resubmission")
                self._journal_terminal(fr, *fr.override)
                self._requests[fid] = fr
                self.metrics.on_replay("unplaced", fleet_id=fid)
                summary["unplaced"] += 1
        self.metrics.on_replay("done", **summary)
        return summary

    def attach_autoscaler(self, autoscaler) -> None:
        """Register the autoscaler ``step()`` ticks (one per fleet
        step).  Called by ``Autoscaler.__init__``."""
        self._autoscaler = autoscaler

    @property
    def autoscaler(self):
        return self._autoscaler

    def _eligible(self, stage: str = "decode") -> List[ReplicaHandle]:
        """Replicas new ``stage`` work may be routed to: role-compatible,
        not draining/retired, not quarantined, circuit not open
        (degraded stays eligible — it is deprioritized by the route
        order, not excluded)."""
        return [h for h in self._handles
                if h.serves(stage)
                and not h.draining and not h.retired
                and h.engine.health.routable]

    def _route_order(self, eligible: List[ReplicaHandle],
                     prompt: np.ndarray
                     ) -> List[Tuple[ReplicaHandle, Optional[int]]]:
        """The replica try-order for one prompt, best first, with each
        candidate's probed prefix-hit length.  Affinity mode: longest
        cached prefix wins within each health band — healthy beats
        SLOW (the straggler detector's deprioritization) beats
        degraded beats slow+degraded — and load breaks ties.
        Round-robin mode: rotate the cursor without probing anyone
        (hit = None; the caller probes only the ACCEPTED replica so
        ``router.prefix_hit_tokens`` stays comparable between the two
        policies without N radix walks per submit)."""
        if not eligible:
            # hedge/failover scans legitimately produce an empty
            # candidate list (the only other replica is draining or
            # quarantined) — that must mean "no order", never a
            # modulo-by-zero out of the round-robin cursor
            return []
        if not self.affinity:
            k = self._rr % len(eligible)
            self._rr += 1
            rotated = eligible[k:] + eligible[:k]
            return [(h, None) for h in rotated]
        probes = [(h, h.engine.core.prefix_probe(prompt))
                  for h in eligible]
        return sorted(
            probes,
            key=lambda p: (p[0].health_rank, -p[1], p[0].load,
                           p[0].index))

    # -------------------------------------------------------- submission
    def submit(self, prompt, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               eos_token_id: Optional[int] = None,
               stream: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None,
               priority: str = "interactive") -> int:
        """Route one request; returns its FLEET id (valid with
        :meth:`result`/:meth:`cancel`/:meth:`stream`/:meth:`purge` on
        this router — engine-local ids never leak to clients).

        Raises :class:`RequestRejected` when no replica can take the
        request: ``no_healthy_replica`` (every decode-capable replica
        excluded by health or drain — a disaggregated fleet always
        needs decode capacity), ``fleet_queue_full`` (the fleet-wide
        ``max_queue`` bound), or the best replica's own rejection
        (``queue_full`` / ``slo_unattainable`` / ``circuit_open``) when
        every eligible replica refused — always carrying the best
        available ``retry_after_s`` hint.  Validation ``ValueError``\\ s
        (empty prompt, prompt+new > max_seq, bad sampling) propagate
        from the first replica tried, before any state is recorded.

        In a disaggregated fleet a long prompt is submitted to a
        PREFILL replica capped at one token; the KV handoff + decode
        resubmission happen transparently inside later :meth:`step`\\ s.
        When every prefill replica refuses, the request falls back to
        the decode-direct path rather than rejecting.

        ``priority`` ("interactive" — the default — or "batch") is the
        request's class: batch work is deferrable inside each engine's
        admission window and is the FIRST thing the brownout ladder
        sheds (``brownout_shed_batch``, with an honest retry hint)
        under sustained overload; at ladder level 2 interactive
        submissions shed too while the queue stays over the bound
        (``brownout_overload``)."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        fleet_id = next(self._ids)
        eligible = self._eligible("decode")
        if not eligible:
            # hint only from replicas that can plausibly recover — a
            # circuit-open replica never will (engine.check_admission
            # hints None for the same reason), so an all-circuit-open
            # fleet correctly reports "no hint" instead of telling
            # clients to retry against the dead replicas' stale history
            self._reject(fleet_id, prompt, "no_healthy_replica",
                         self._best_hint(
                             [h for h in self._handles
                              if h.serves("decode") and not h.retired
                              and h.engine.health.state != CIRCUIT_OPEN]))
        # the brownout ladder (docs/serving.md "Tail latency"): shed
        # batch first, then — at level 2, while the queue still sits
        # over the ENTER bound — everyone, always with the honest
        # live-metrics retry hint.  While browned out, every submit is
        # ALSO a control observation — EXIT-only: an idle fleet whose
        # work drained before the exit hysteresis completed would
        # otherwise shed batch forever (rejections enqueue nothing, so
        # step() — the normal tick site — never runs again), while
        # escalation stays a per-step judgement
        if self._brownout.level > 0:
            self._brownout_tick(exit_only=True)
        if self._brownout.level >= 1 and priority == "batch":
            self.metrics.c_shed_batch.inc()
            self.metrics.on_brownout("shed", self._brownout.level,
                                     fleet_id=fleet_id)
            self._reject(fleet_id, prompt, "brownout_shed_batch",
                         self._best_hint(eligible))
        if self._brownout.level >= 2 \
                and self.queue_depth >= self._brownout.depth:
            self._reject(fleet_id, prompt, "brownout_overload",
                         self._best_hint(eligible))
        if self.max_queue is not None \
                and self.queue_depth >= self.max_queue:
            self._reject(fleet_id, prompt, "fleet_queue_full",
                         self._best_hint(eligible))
        fr = _FleetRequest(fleet_id, prompt, max_new_tokens, sampling,
                           eos_token_id, stream, deadline_s,
                           ttft_deadline_s, priority=priority)
        fr.submit_time = time.perf_counter()
        rejections: List[Tuple[int, RequestRejected]] = []
        # disaggregated two-phase path: long prompts needing >1 output
        # token try the prefill plane first (prefix affinity among the
        # prefill replicas); the decode-direct order is the fallback
        prefill_order: List[Tuple[ReplicaHandle, Optional[int]]] = []
        if self.prefill_threshold is not None and max_new_tokens > 1 \
                and prompt.size >= self.prefill_threshold:
            pre = self._eligible("prefill")
            if pre:
                # the decode phase must eventually fit somewhere: a
                # request that can never be placed on ANY decode-capable
                # replica is a caller bug, surfaced loudly here instead
                # of as a mid-handoff failure.  Capacity is a FLEET
                # property — measured over every decode-capable replica
                # (health is transient; a quarantined big replica comes
                # back with the same max_seq), not just the currently
                # eligible ones
                fleet_max_seq = max(
                    h.engine.core.pool.max_seq for h in self._handles
                    if h.serves("decode") and not h.retired)
                if prompt.size + max_new_tokens > fleet_max_seq:
                    raise ValueError(
                        f"prompt_len {prompt.size} + max_new_tokens "
                        f"{max_new_tokens} exceeds every decode "
                        f"replica's max_seq — the post-handoff "
                        f"submission could never be placed")
                prefill_order = self._route_order(pre, prompt)
        for h, hit in prefill_order:
            try:
                rid = self._submit_to(h, fr, max_new=1)
            except RequestRejected as e:
                rejections.append((h.index, e))
                continue
            fr.role_stage = "prefill"
            return self._place(fr, h, rid, hit)
        for h, hit in self._route_order(eligible, prompt):
            try:
                rid = self._submit_to(h, fr)
            except RequestRejected as e:
                rejections.append((h.index, e))
                continue
            return self._place(fr, h, rid, hit)
        # every eligible replica rejected: surface the BEST replica's
        # reason with the best (smallest, still-finite) retry hint,
        # carrying EVERY replica's own rejection for debuggability
        hints = [e.retry_after_s for _, e in rejections
                 if e.retry_after_s is not None]
        per_replica = [{"replica": i, "reason": e.reason,
                        "retry_after_s": e.retry_after_s}
                       for i, e in rejections]
        self._reject(fleet_id, prompt, rejections[0][1].reason,
                     min(hints) if hints else None,
                     per_replica=per_replica)

    def _place(self, fr: _FleetRequest, h: ReplicaHandle,
               rid: int, hit: Optional[int]) -> int:
        """Record a freshly accepted fleet submission's ownership."""
        fr.replica, fr.engine_rid = h.index, rid
        fr.attempts = 1
        h.routed += 1
        self._requests[fr.fleet_id] = fr
        self._live.add(fr.fleet_id)
        if self.journal is not None and not fr.journaled_submit:
            # once per fleet id, EVER: a recovered request was already
            # journaled by its first incarnation (recover() pre-sets
            # the flag), and failovers re-place without re-journaling
            fr.journaled_submit = True
            self.journal.append_submit(
                fr.fleet_id, fr.prompt, fr.max_new_tokens,
                sampling=None if fr.sampling is None
                else dataclasses.asdict(fr.sampling),
                eos_token_id=fr.eos_token_id,
                deadline_s=fr.deadline_s,
                ttft_deadline_s=fr.ttft_deadline_s,
                priority=fr.priority)
        if hit is None:             # round-robin: probe the winner only
            hit = h.engine.core.prefix_probe(fr.prompt)
        self.metrics.on_route(fr.fleet_id, h.index, hit)
        return fr.fleet_id

    def _journal_terminal(self, fr: _FleetRequest, status: str,
                          reason) -> None:
        """Write one fleet request's terminal record — exactly once per
        fleet id across every settle site (scan, cancel, purge, kill,
        handoff exhaustion, recovery expiry)."""
        if self.journal is None or fr.journaled_terminal \
                or not fr.journaled_submit:
            return
        fr.journaled_terminal = True
        self.journal.append_terminal(fr.fleet_id, status,
                                     reason or status,
                                     delivered=fr.delivered)

    def _journal_progress(self) -> None:
        """Batch this step's delivered high-water marks into ONE journal
        record (host ints the dedup wrapper already tracks)."""
        updates = {}
        for fid in self._live:
            fr = self._requests[fid]
            if fr.delivered > fr.journal_hwm:
                updates[fid] = fr.journal_hwm = fr.delivered
        self.journal.append_progress(updates)

    def _reject(self, fleet_id: int, prompt: np.ndarray, reason: str,
                retry_after_s: Optional[float],
                per_replica: Optional[List[Dict[str, object]]] = None):
        self.metrics.on_reject(reason)
        status_reason = reason
        if per_replica:
            # the output's terminal record names every replica's own
            # refusal, not just the winning reason — the multi-replica
            # rejection path's debuggability contract
            detail = "; ".join(
                f"replica {d['replica']}: {d['reason']}"
                for d in per_replica)
            status_reason = f"{reason} [{detail}]"
        out = RequestOutput(
            request_id=fleet_id, prompt=prompt, tokens=[], finished=True,
            finish_reason=None, ttft_s=None, status="rejected",
            status_reason=status_reason)
        raise RequestRejected(reason, retry_after_s, output=out,
                              per_replica=per_replica)

    def _best_hint(self, handles: Sequence[ReplicaHandle]
                   ) -> Optional[float]:
        hints = [h.engine.metrics.retry_after_hint() for h in handles]
        hints = [x for x in hints if x is not None]
        return min(hints) if hints else None

    def _submit_to(self, h: ReplicaHandle, fr: _FleetRequest,
                   now: Optional[float] = None,
                   max_new: Optional[int] = None) -> int:
        """Submit (or RE-submit, on failover/handoff) one fleet request
        to a replica, with the deadline budgets shrunk by the time
        already spent — a failover must not silently grant a fresh
        deadline.  A request whose first token was already delivered
        carries no TTFT deadline into the retry (the client's TTFT was
        met).  ``max_new`` overrides the client's budget — the
        prefill-stage submission caps at ONE token (the TTFT token; the
        decode phase regenerates it deduped and continues)."""
        if now is None:
            now = time.perf_counter()
        elapsed = max(now - fr.submit_time, 0.0)
        deadline = fr.deadline_s
        if deadline is not None:
            deadline = max(deadline - elapsed, 0.0)
        ttft = fr.ttft_deadline_s
        if ttft is not None:
            ttft = None if fr.delivered > 0 \
                else max(ttft - elapsed, 0.0)
        return h.engine.submit(
            fr.prompt,
            max_new_tokens=fr.max_new_tokens if max_new is None
            else max_new,
            sampling=fr.sampling, eos_token_id=fr.eos_token_id,
            stream=self._fleet_stream(fr),
            deadline_s=deadline, ttft_deadline_s=ttft,
            priority=fr.priority)

    def _fleet_stream(self, fr: _FleetRequest) -> Callable:
        """The exactly-once dedup wrapper: every replica attempt streams
        through it; positions below the delivered high-water mark (a
        failover retry regenerating the prefix it already served) are
        swallowed, so the client sees each token position once."""
        def cb(req, tok):
            pos = len(req.tokens) - 1   # _emit appends before calling
            if pos < fr.delivered:
                return
            fr.delivered = pos + 1
            if fr.client_stream is not None:
                fr.client_stream(req, tok)
        return cb

    # --------------------------------------------------------- execution
    def step(self) -> int:
        """One fleet iteration: step every live replica (timed — the
        straggler detector's input), run the failover + hedge scans
        over live requests, pump pending KV handoffs, tick the
        brownout ladder, journal this step's delivered high-water
        marks, tick the autoscaler (when attached) and refresh the
        fleet gauges.  Returns the number of requests still in flight
        fleet-wide."""
        slow_victim, slow_armed = -1, None
        if self.faults is not None:
            # the replica_crash chaos point: SIGKILL the lowest-index
            # live replica (deterministic for a deterministic workload
            # — the chaos suite's replay-parity invariant needs the
            # same arming to kill the same replica every run)
            armed = self.faults.check("replica_crash")
            if armed is not None:
                for h in self._handles:
                    if not h.retired:
                        self.kill(h.index)
                        break
            # the replica_slow chaos point: straggle the lowest-index
            # live replica at the ROUTER (a sleep inside its timed
            # step window — engine internals untouched), deterministic
            # for the same reason as replica_crash
            slow_armed = self.faults.check("replica_slow")
            if slow_armed is not None:
                for h in self._handles:
                    if not h.retired:
                        slow_victim = h.index
                        break
        for h in self._handles:
            if h.retired:
                continue
            # latency is observed only on steps that SERVED something:
            # an idle replica's near-zero step time is not a health
            # baseline, and feeding it in would make any busy peer —
            # i.e. exactly the replica affinity concentrates load on —
            # look like an outlier
            busy = h.engine.core.scheduler.has_work()
            h._observed = busy
            t0 = time.perf_counter()
            if h.index == slow_victim and busy:
                # straggle only SERVING steps: an idle victim's sleep
                # is never observed into the EWMA (the busy gate
                # below), so it would burn wall clock for zero
                # detection value through every drain tail
                time.sleep(slow_armed.seconds)
            h.engine.step()
            if busy:
                h.observe_step(time.perf_counter() - t0)
        self._detect_stragglers()
        self._scan_failover()
        self._scan_hedges()
        self._pump_handoffs()
        self._brownout_tick()
        if self.journal is not None:
            self._journal_progress()
        if self._autoscaler is not None:
            self._autoscaler.tick()
        self.metrics.publish(self._handles)
        return self.in_flight

    @property
    def brownout_level(self) -> int:
        """The overload-shedding ladder's current level (0 = normal;
        docs/serving.md "Tail latency")."""
        return self._brownout.level

    def _brownout_tick(self, exit_only: bool = False) -> None:
        """One brownout control observation of the live queue depth,
        with the transition telemetry."""
        transition = self._brownout.update(self.queue_depth,
                                           exit_only=exit_only)
        if transition is not None:
            self.metrics.on_brownout(transition, self._brownout.level,
                                     queue_depth=self.queue_depth)

    # ------------------------------------------------------- stragglers
    def _detect_stragglers(self) -> None:
        """The fleet-relative outlier rule (docs/serving.md "Tail
        latency"): a replica whose step-latency EWMA exceeds its
        PEERS' median by ``slow_threshold`` for ``slow_hysteresis``
        consecutive fleet steps is marked slow; it clears through the
        same hysteresis.  The median excludes the replica under test —
        in a small fleet a straggler drags a self-inclusive median up
        toward its own latency and can mask itself (at n=2 a 2x
        threshold could NEVER fire).  Needs at least two live replicas
        — "slow" is a relative judgement, and a fleet of one has no
        peer to be slower than."""
        live = [h for h in self._handles
                if not h.retired and h.step_ewma_s > 0.0]
        if len(live) < 2:
            # no peer, no relative judgement — and a STANDING mark must
            # not freeze into stale evidence (a slow_ticks count the
            # autoscaler would act on) when the fleet shrinks around
            # it: clear it and let a future peer comparison re-earn it
            # through the normal hysteresis
            for h in self._handles:
                if not h.retired and h.engine.health.slow:
                    h.engine.health.clear_slow()
                    h.slow_ticks = 0
                    h._slow_streak = h._fast_streak = 0
                    self.metrics.on_slow("clear", h.index,
                                         reason="no live peer to "
                                                "compare against")
            return
        for h in live:
            health = h.engine.health
            if not h._observed:
                # no busy step this round: the frozen EWMA proves
                # nothing either way — streaks and slow_ticks hold (an
                # idle deprioritized replica must neither clear its
                # mark on stale data nor accrue replacement pressure
                # while it serves nothing)
                continue
            median = float(np.median([p.step_ewma_s for p in live
                                      if p is not h]))
            if median <= 0.0:
                continue
            bar = median * self.slow_threshold
            if h.step_ewma_s > bar:
                h._fast_streak = 0
                h._slow_streak += 1
                if not health.slow \
                        and h._slow_streak >= self.slow_hysteresis:
                    health.mark_slow(
                        f"step EWMA {h.step_ewma_s:.4f}s > "
                        f"{self.slow_threshold:g}x fleet median "
                        f"{median:.4f}s for {h._slow_streak} steps")
                    self.metrics.on_slow(
                        "mark", h.index,
                        ewma_s=round(h.step_ewma_s, 4),
                        fleet_median_s=round(median, 4))
            else:
                h._slow_streak = 0
                h._fast_streak += 1
                if health.slow \
                        and h._fast_streak >= self.slow_hysteresis:
                    health.clear_slow()
                    h.slow_ticks = 0
                    self.metrics.on_slow(
                        "clear", h.index,
                        ewma_s=round(h.step_ewma_s, 4),
                        fleet_median_s=round(median, 4))
            h.slow_ticks = h.slow_ticks + 1 if health.slow else 0

    def has_work(self) -> bool:
        return (any(h.engine.core.scheduler.has_work()
                    for h in self._handles if not h.retired)
                or self._handoffs.pending > 0)

    def _progress(self) -> int:
        return (sum(h.engine.core.progress_counter
                    for h in self._handles)
                + self.metrics.c_failovers.value
                + self.metrics.c_failover_exhausted.value
                # every handoff transition is fleet progress — a staged
                # transfer waiting for a slot must not trip the stall
                # detector while it is still advancing
                + self._handoffs.staged + self._handoffs.committed
                + self._handoffs.aborted + self._handoffs.retries
                # every hedge transition (issue, win, failed issue) is
                # fleet progress — a hedge race mid-flight must not
                # trip the stall detector while it is still advancing
                + self.metrics.c_hedges.value
                + self.metrics.c_hedge_wins.value
                + self.metrics.c_hedge_failed.value)

    def run_until_complete(self, max_steps: Optional[int] = None,
                           stall_steps: Optional[int] = 64) -> int:
        """Step until every replica drains; returns steps taken.  The
        stall detector watches FLEET progress (token emits, admissions,
        dispositions, failovers) so a wedged replica raises
        :class:`EngineStalledError` with a per-replica snapshot instead
        of spinning."""
        steps = stalled = 0
        last = self._progress()
        while self.has_work():
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain within {max_steps} steps")
            if stall_steps is not None and self.fleet_dead:
                # every replica killed/retired/circuit-open with work
                # still outstanding: no number of idle steps can drain
                # it — fail fast with the descriptive snapshot
                # (routable count, journal position) instead of
                # spinning to the generic no-progress stall
                raise EngineStalledError(0, self.stall_snapshot())
            self.step()
            steps += 1
            p = self._progress()
            if p != last:
                last, stalled = p, 0
            else:
                stalled += 1
                if stall_steps is not None and stalled >= stall_steps \
                        and self.has_work():
                    raise EngineStalledError(stalled,
                                             self.stall_snapshot())
        return steps

    def stream(self, fleet_id: int) -> Iterator[int]:
        """Yield the request's tokens as they are generated, stepping
        the FLEET while waiting — so health scans and failovers keep
        running; the iterator transparently follows the request onto a
        failover target (the dedup wrapper guarantees each yielded
        position was generated for this client exactly once)."""
        fr = self._record(fleet_id)
        seen = 0
        while True:
            req = self._handles[fr.replica].engine._requests.get(
                fr.engine_rid)
            toks = req.tokens if req is not None else []
            while seen < len(toks):
                yield toks[seen]
                seen += 1
            if fleet_id not in self._live:
                return
            self.step()

    # ----------------------------------------------------------- hedging
    def _hedge_delay_s(self) -> float:
        """The p95-based hedge delay: a request is never duplicated
        before it has been given the fleet's p95 TTFT to show progress
        (the Tail-at-Scale rule — hedge the outliers, not the median).
        0.0 with no history: a cold fleet hedges on projection alone."""
        hist = self.registry.get("serving.ttft_s")
        if hist is None:
            return 0.0
        q = hist.quantile(0.95)
        return float(q) if q is not None else 0.0

    def _projected_completion_s(self, fr: _FleetRequest,
                                h: ReplicaHandle, req,
                                now: float) -> Optional[float]:
        """Projected submit→finish seconds for ``fr`` on its CURRENT
        replica: time already spent, plus the live per-replica step
        latency (the straggler detector's EWMA — one decode position
        per step) times the positions left, plus the queue ahead while
        the request has not been admitted.  None without latency
        history — a projection invented from zero data must not issue
        hedges."""
        ewma = h.step_ewma_s
        if ewma <= 0.0:
            return None
        elapsed = now - fr.submit_time
        done = 0 if req is None else len(req.tokens)
        remaining = max(fr.max_new_tokens - done, 0)
        queued_s = 0.0
        if req is not None and not req.finished \
                and req.admit_time is None:
            # still waiting for a slot: the position term is the
            # replica's own live TTFT projection (queue drain at its
            # measured completion rate — the same estimate SLO
            # rejection uses), falling back to one step per queued
            # request on a history-less replica
            depth = h.engine.core.scheduler.queue_depth
            est = h.engine.metrics.projected_ttft_s(depth)
            queued_s = est if est is not None else depth * ewma
        return elapsed + remaining * ewma + queued_s

    def _scan_hedges(self) -> None:
        """Issue hedges for deadline-at-risk requests (docs/serving.md
        "Tail latency" hedge state machine).  Runs after the failover
        scan each fleet step; suspended entirely under brownout —
        duplicate work is load an overloaded fleet must not amplify."""
        if not self.hedging or self._brownout.level >= 1 \
                or not self._live:
            return
        now = time.perf_counter()
        delay = None                     # computed lazily, once per scan
        for fid in list(self._live):
            fr = self._requests[fid]
            if (fr.hedged or fr.attempts >= 2
                    or fr.deadline_s is None
                    or fr.role_stage != "decode"):
                continue
            h = self._handles[fr.replica]
            req = h.engine._requests.get(fr.engine_rid)
            if req is None or req.finished:
                continue
            if delay is None:
                delay = self._hedge_delay_s()
            # the delay is additionally bounded by a quarter of the
            # request's own deadline: waiting the fleet p95 before
            # hedging a SHORT-deadline request would spend the budget
            # the hedge exists to protect
            if now - fr.submit_time < min(delay, 0.25 * fr.deadline_s):
                continue
            proj = self._projected_completion_s(fr, h, req, now)
            if proj is None or proj <= fr.deadline_s:
                continue
            self.issue_hedge(fr, now=now, projected_s=proj)

    def issue_hedge(self, fr: _FleetRequest, now: Optional[float] = None,
                    projected_s: Optional[float] = None) -> bool:
        """Issue THE duplicate submission for a deadline-at-risk fleet
        request onto the best OTHER healthy replica — the failover
        shape applied preemptively: same fleet-id idempotency key, same
        delivered-high-water-mark dedup (both attempts stream through
        the one wrapper, so the client sees each token position exactly
        once), same attempts ≤ 2 budget and deadline shrinking as
        ``_reattribute``.  One hedge per fleet id, EVER — issuing (even
        a failed issue: the opportunity is spent) sets ``fr.hedged``;
        only a fleet state with NO candidate target at all (the sole
        peer draining/quarantined) is a no-op the scan may retry.
        Balance with :meth:`resolve_hedge` (the hedge won the race) or
        :meth:`purge_hedge` (the hedge lost and unwinds) — a registered
        graftlint ``ResourcePair``.  Returns True when a replica
        accepted the duplicate; a False (every target rejected, or the
        ``hedge_submit`` chaos point fired) fails CLOSED — the primary
        attempt is untouched."""
        if fr.hedged or fr.attempts >= 2:
            return False
        if now is None:
            now = time.perf_counter()
        targets = [h for h in self._eligible("decode")
                   if h.index != fr.replica]
        if not targets:
            # nowhere to hedge RIGHT NOW (the only peer is draining or
            # quarantined): a no-op, not a spent opportunity — the
            # scan retries once a peer recovers, deadline permitting
            return False
        fr.hedged = True
        if self.faults is not None:
            armed = self.faults.check("hedge_submit")
            if armed is not None:
                # injected submission fault: the duplicate dies before
                # landing anywhere — nothing to unwind, primary stands
                self.metrics.c_hedge_failed.inc()
                self.metrics.on_hedge(
                    "failed", fr.fleet_id,
                    reason="injected fault at hedge_submit")
                return False
        for h, hit in self._route_order(targets, fr.prompt):
            try:
                rid = self._submit_to(h, fr, now=now)
            except (RequestRejected, ValueError):
                # ValueError: a heterogeneous fleet — this target's
                # max_seq cannot hold the request the primary's could.
                # A hedge runs inside the step loop, so validation
                # refusals mean "next target", never a raise that
                # would strand the whole fleet mid-serve
                continue
            fr.hedge_replica, fr.hedge_rid = h.index, rid
            fr.attempts += 1
            h.routed += 1
            self.metrics.c_hedges.inc()
            self.metrics.on_hedge(
                "issue", fr.fleet_id, primary=fr.replica,
                target=h.index, deadline_s=fr.deadline_s,
                projected_s=None if projected_s is None
                else round(projected_s, 4))
            return True
        self.metrics.c_hedge_failed.inc()
        self.metrics.on_hedge("failed", fr.fleet_id,
                              reason="every eligible replica rejected "
                                     "the duplicate")
        return False

    def resolve_hedge(self, fr: _FleetRequest, reason: str) -> None:
        """The hedge won the race (it finished first, or the primary
        died under it): promote it to the authoritative attempt and
        purge the surrendered primary's engine record — the loser's
        slot, staging rows and radix pins release through the normal
        cancel-on-purge unwind (a KILLED primary's dead engine is left
        alone; its state is unreadable by definition).  A no-op when no
        hedge is live — resolving twice must never repoint the request
        at the -1 sentinel (which would negative-index into the LAST
        replica's handle)."""
        if fr.hedge_rid < 0:
            return
        src, src_rid = fr.replica, fr.engine_rid
        fr.history.append((src, src_rid, reason))
        src_h = self._handles[src]
        if not src_h.killed and src_rid in src_h.engine._requests:
            src_h.engine.purge(src_rid)
        fr.replica, fr.engine_rid = fr.hedge_replica, fr.hedge_rid
        fr.hedge_replica = fr.hedge_rid = -1
        self.metrics.c_hedge_wins.inc()
        self.metrics.on_hedge("win", fr.fleet_id, winner=fr.replica,
                              loser=src, reason=str(reason)[:200])

    def purge_hedge(self, fr: _FleetRequest, reason: str) -> None:
        """The hedge lost the race (the primary finished first, or the
        client settled the request, or the hedge's replica died): unwind
        the duplicate completely — its engine record is purged (cancel-
        on-purge returns the slot and every pin), so the loser leaves
        ZERO state behind on its replica.  Idempotent once the hedge is
        resolved."""
        if fr.hedge_rid < 0:
            return
        h = self._handles[fr.hedge_replica]
        if not h.killed and fr.hedge_rid in h.engine._requests:
            h.engine.purge(fr.hedge_rid)
        self.metrics.on_hedge("purge", fr.fleet_id,
                              replica=fr.hedge_replica,
                              reason=str(reason)[:200])
        fr.hedge_replica = fr.hedge_rid = -1

    def _settle_hedge_race(self, fr: _FleetRequest) -> None:
        """One scan pass over a LIVE hedge race: the first attempt to
        reach ``finished`` wins and the loser is purged; an attempt
        that dies (failed / deadline) while its peer still runs
        surrenders to the peer; both terminal keeps the primary's
        record standing and unwinds the hedge."""
        pri = self._handles[fr.replica].engine._requests.get(
            fr.engine_rid)
        hed = self._handles[fr.hedge_replica].engine._requests.get(
            fr.hedge_rid)
        if hed is None:
            # the hedge record vanished underneath us (its replica was
            # retired mid-race) — the primary stands alone
            fr.hedge_replica = fr.hedge_rid = -1
            return
        if pri is None:
            self.resolve_hedge(fr, "primary record lost")
            return
        if pri.finished and pri.status == "finished":
            self.purge_hedge(fr, "primary finished first")
        elif hed.finished and hed.status == "finished":
            self.resolve_hedge(fr, "hedge finished first")
        elif pri.finished and hed.finished:
            self.purge_hedge(fr, f"both attempts terminal "
                                 f"({pri.status} / {hed.status})")
        elif pri.finished:
            self.resolve_hedge(fr, f"primary {pri.status}: "
                                   f"{pri.status_reason}")
        elif hed.finished:
            self.purge_hedge(fr, f"hedge {hed.status}: "
                                 f"{hed.status_reason}")

    # ---------------------------------------------------------- failover
    def _scan_failover(self) -> None:
        """Settle finished fleet requests; resubmit replica-attributed
        failures ONCE to the best healthy replica; open KV handoffs for
        prefill-stage requests whose prefill completed.  Runs after
        every fleet step, off any engine's hot path."""
        if not self._live:
            return
        for fid in list(self._live):
            fr = self._requests[fid]
            if fr.hedge_rid >= 0:
                # a live hedge race settles BEFORE the terminal scan:
                # first finished wins, the loser is purged, and fr
                # points at the winner below
                self._settle_hedge_race(fr)
            # the engine-internal record is authoritative and cheap;
            # result() would build a RequestOutput copy per scan
            req = self._handles[fr.replica].engine._requests.get(
                fr.engine_rid)
            if req is None or not req.finished:
                continue
            if fr.role_stage == "prefill" and req.status == "finished":
                # the one-token prefill run completed.  A first token
                # that already ended the request (eos, or a one-token
                # budget that took the decode-direct guard's gap) is
                # genuinely done; otherwise open the KV handoff and
                # keep the fleet id live until the decode phase owns it
                if req.finish_reason == "eos" or fr.max_new_tokens <= 1:
                    self._journal_terminal(fr, req.status,
                                           req.status_reason)
                    self._live.discard(fid)
                    continue
                if fid not in self._handoffs.records:
                    self._stage_handoff(fr)
                continue
            if (self.failover and req.status == "failed"
                    and fr.attempts < 2
                    and not str(req.status_reason or "").startswith(
                        _CLIENT_FAULT_PREFIX)):
                if self._try_failover(fr, req):
                    continue        # re-owned: stays live on the target
            self._journal_terminal(fr, req.status, req.status_reason)
            self._live.discard(fid)

    def _try_failover(self, fr: _FleetRequest, failed_req) -> bool:
        """Resubmit one failed fleet request.  Returns True when a
        healthy replica accepted it (the router map now points there);
        False leaves the terminal ``failed`` standing.  A request that
        died during its PREFILL stage fails over as a FULL submission
        onto the decode plane — the prefill shortcut already proved
        unlucky, and decode/unified replicas prefill fine."""
        now = time.perf_counter()
        if fr.deadline_s is not None \
                and now - fr.submit_time >= fr.deadline_s:
            self.metrics.on_failover_exhausted(
                fr.fleet_id, fr.replica, "deadline already spent")
            return False
        # prefer a DIFFERENT replica; fall back to the (recovered)
        # origin only when it is the sole eligible one
        eligible = self._eligible("decode")
        targets = [h for h in eligible if h.index != fr.replica] \
            or eligible
        if not targets:
            self.metrics.on_failover_exhausted(
                fr.fleet_id, fr.replica, "no healthy replica")
            return False
        src, src_rid = fr.replica, fr.engine_rid
        reason = failed_req.status_reason or "failed"
        for h, hit in self._route_order(targets, fr.prompt):
            try:
                rid = self._submit_to(h, fr, now=now)
            except RequestRejected:
                continue
            # drop the surrendered attempt's record from the old engine
            # (terminal — purge only releases the host-side reference)
            fr.history.append((src, src_rid, reason))
            self._handles[src].engine.purge(src_rid)
            fr.replica, fr.engine_rid = h.index, rid
            fr.role_stage = "decode"
            fr.attempts += 1
            h.routed += 1
            self.metrics.on_failover(fr.fleet_id, src, h.index, reason)
            return True
        self.metrics.on_failover_exhausted(
            fr.fleet_id, fr.replica, "every healthy replica rejected")
        return False

    # --------------------------------------------------------- handoffs
    def _stage_handoff(self, fr: _FleetRequest) -> None:
        """Open the KV handoff for a prefill-stage request whose
        prefill just finished: pin its block path on the source replica
        and let :meth:`_pump_handoffs` drive the transfer."""
        src = self._handles[fr.replica]
        rec = self._handoffs.stage(fr.fleet_id, src, fr.prompt)
        try:
            self.metrics.c_handoff_staged.inc()
            self.metrics.on_handoff("stage", fr.fleet_id, rec.src, -1,
                                    tokens=rec.tokens)
        except BaseException:
            # telemetry must never leak the staged pin
            self._handoffs.abort(rec, "stage telemetry failed")
            raise

    def _handoff_dst(self, fr: _FleetRequest,
                     tokens: int) -> Optional[ReplicaHandle]:
        """The transfer target: the healthiest, lightest-loaded decode
        replica (load on the decode side — the prefill side already
        spent its affinity), skipping replicas with no free staging
        slot while blocks actually need to move."""
        targets = sorted(
            self._eligible("decode"),
            key=lambda h: (h.health_rank, h.load, h.index))
        for h in targets:
            if tokens == 0 or h.engine.core.pool.free_slots > 0:
                return h
        return None

    def _pump_handoffs(self) -> None:
        """Advance every pending handoff one transition per fleet step:
        staged records transfer (or defer while no destination can
        stage them, bounded by the manager's patience), successful
        transfers commit + resubmit, terminal failures fall to the
        recovery path.  Any record whose request was settled meanwhile
        (cancel/purge) is aborted so its pin cannot leak."""
        for fid in list(self._handoffs.records):
            rec = self._handoffs.records.get(fid)
            if rec is None:
                continue
            fr = self._requests.get(fid)
            if fr is None or fid not in self._live:
                self._handoffs.abort(rec, "request settled mid-handoff")
                self.metrics.c_handoff_aborted.inc()
                self.metrics.on_handoff("abort", fid, rec.src, rec.dst,
                                        reason=rec.reason)
                continue
            dst = self._handoff_dst(fr, rec.tokens)
            if dst is None:
                rec.deferred_steps += 1
                if rec.deferred_steps > self._handoffs.stage_patience:
                    self._handoffs.abort(
                        rec, "no decode replica could stage the "
                             "transfer within patience")
                    self._abort_metrics(rec)
                    self._recover_handoff(fr, rec)
                continue
            src = self._handles[rec.src]
            if self._handoffs.transfer(rec, src, dst, fr.prompt):
                self._commit_handoff(fr, rec, dst)
            elif rec.state == ABORTED:
                self._abort_metrics(rec)
                self._recover_handoff(fr, rec)
            else:
                # retryable in-flight fault: the record fell back to
                # staged with the pin held; the next pump retries
                self.metrics.c_handoff_retries.inc()
                self.metrics.on_handoff("retry", fid, rec.src, rec.dst,
                                        attempt=rec.transfer_attempts)

    def _abort_metrics(self, rec) -> None:
        self.metrics.c_handoff_aborted.inc()
        self.metrics.on_handoff("abort", rec.fleet_id, rec.src, rec.dst,
                                reason=rec.reason)

    def _commit_handoff(self, fr: _FleetRequest, rec,
                        dst: ReplicaHandle) -> None:
        """Seal a successful transfer and hand the decode phase to the
        destination.  A commit-stage fault (the ``handoff_commit``
        chaos point) aborts instead — the blocks already moved, so the
        recovery resubmission simply finds them cached."""
        try:
            self._handoffs.commit(rec)
        except Exception as e:
            self._handoffs.abort(rec, f"commit fault: {e!r}")
            self._abort_metrics(rec)
            self._recover_handoff(fr, rec)
            return
        self.metrics.c_handoff_committed.inc()
        if rec.blocks_moved:
            self.metrics.c_handoff_blocks.inc(rec.blocks_moved)
        self.metrics.on_handoff("commit", fr.fleet_id, rec.src, rec.dst,
                                blocks=rec.blocks_moved,
                                tokens=rec.tokens)
        self._place_decode_phase(
            fr, first=dst,
            why=f"handoff committed ({rec.blocks_moved} blocks)")

    def _recover_handoff(self, fr: _FleetRequest, rec) -> None:
        """An aborted handoff's fallback: RE-PREFILL on the decode
        side — the request resubmits in full with no transferred state
        (whatever blocks DID land are found by normal admission
        matching).  When no decode replica accepts, the request fails
        terminally at the router (the engine-side record is a stale
        one-token view, so the terminal stamp lives on the fleet
        record)."""
        self._place_decode_phase(
            fr, first=None, why=f"handoff aborted: {rec.reason}")

    def _place_decode_phase(self, fr: _FleetRequest,
                            first: Optional[ReplicaHandle],
                            why: str) -> None:
        """Resubmit the full request for its decode phase, preferring
        ``first`` (the transfer destination — its cache is warm), then
        every other eligible decode replica.  Exhaustion is terminal.
        A deadline that expired while the handoff waited is terminal
        ``deadline_exceeded`` — not a zero-budget resubmission whose
        failure would masquerade as a placement problem (the same
        short-circuit ``_try_failover`` performs)."""
        now = time.perf_counter()
        if fr.deadline_s is not None \
                and now - fr.submit_time >= fr.deadline_s:
            self.metrics.on_handoff("expired", fr.fleet_id, fr.replica,
                                    -1, reason=why)
            fr.override = ("deadline_exceeded",
                           f"end-to-end deadline {fr.deadline_s}s "
                           f"spent during the KV handoff ({why})")
            self._journal_terminal(fr, *fr.override)
            self._live.discard(fr.fleet_id)
            return
        targets = [] if first is None else [first]
        targets += [h for h in self._eligible("decode")
                    if h not in targets]
        src, src_rid = fr.replica, fr.engine_rid
        for h in targets:
            try:
                rid = self._submit_to(h, fr, now=now)
            except RequestRejected:
                continue
            fr.history.append((src, src_rid, why))
            self._handles[src].engine.purge(src_rid)
            fr.replica, fr.engine_rid = h.index, rid
            fr.role_stage = "decode"
            fr.handoffs += 1
            h.routed += 1
            return
        self.metrics.c_handoff_failed.inc()
        self.metrics.on_handoff("failed_terminal", fr.fleet_id, src, -1,
                                reason=why)
        fr.override = ("failed",
                       f"no decode replica accepted the post-handoff "
                       f"submission ({why})")
        self._journal_terminal(fr, *fr.override)
        self._live.discard(fr.fleet_id)

    # ------------------------------------------------------------ drains
    def drain(self, replica: int) -> None:
        """Stop routing NEW work to ``replica`` (index) while its
        in-flight requests finish normally — the graceful half of
        taking a replica out of rotation.  Balance with
        :meth:`undrain` — or :meth:`retire`, for permanent removal —
        (a registered graftlint ``ResourcePair``): a drain leaked on an
        exception path silently shrinks the fleet.

        Edge semantics (pinned by unit tests): an out-of-range index
        raises the descriptive ``KeyError`` every replica lookup uses;
        draining an ALREADY-draining or retired replica raises
        ``ValueError`` — a double drain is always a caller bug (two
        owners both believe they hold the drain window)."""
        h = self._handle(replica)
        if h.retired:
            raise ValueError(
                f"replica {replica} is retired — it left the rotation "
                f"permanently and cannot be drained")
        if h.draining:
            raise ValueError(
                f"replica {replica} is already draining — a second "
                f"drain means two owners think they hold the drain "
                f"window; undrain() first if that is intended")
        h.draining = True
        self.metrics.on_drain(replica, "drain")
        self.metrics.publish(self._handles)

    def undrain(self, replica: int) -> None:
        """Return a drained replica to the routing rotation
        (idempotent — undraining a non-draining replica is a no-op;
        out-of-range indices still raise the descriptive KeyError;
        retired replicas can never re-enter rotation)."""
        h = self._handle(replica)
        if h.retired:
            raise ValueError(
                f"replica {replica} is retired — its engine is closed "
                f"and it cannot return to rotation")
        h.draining = False
        self.metrics.on_drain(replica, "undrain")
        self.metrics.publish(self._handles)

    def drained(self, replica: int) -> bool:
        """True once a draining replica has no queued or in-flight
        work left — safe to rebuild/retire."""
        h = self._handle(replica)
        return h.draining and not h.engine.core.scheduler.has_work()

    # ----------------------------------------------------------- results
    def _record(self, fleet_id: int) -> _FleetRequest:
        fr = self._requests.get(fleet_id)
        if fr is None:
            raise KeyError(
                f"unknown fleet request_id {fleet_id} — never submitted "
                f"to this router, or already purged")
        return fr

    def _migrating(self, fr: _FleetRequest, out: RequestOutput) -> bool:
        """True while a prefill-stage request's one-token run has
        finished but the router still owes it a decode phase (handoff
        staged/pending or about to be) — the engine-side 'finished'
        is an interim view, not the request's terminal state."""
        return (fr.fleet_id in self._live
                and fr.role_stage == "prefill"
                and out.finished and out.status == "finished"
                and out.finish_reason != "eos"
                and fr.max_new_tokens > 1)

    def result(self, fleet_id: int) -> RequestOutput:
        """The request's current view FROM ITS OWNING REPLICA (the map
        is authoritative across failovers AND handoffs), re-keyed to
        the fleet id.  While a handoff is mid-flight the view shows
        the prefill side's delivered prefix with ``finished=False`` —
        a polling client must not mistake the one-token prefill run
        for the request's terminal state.  A router-level terminal
        stamp (handoff placement exhausted) overrides the stale engine
        record."""
        fr = self._record(fleet_id)
        if fr.replica < 0:
            # never placed on any engine (a recovered request whose
            # deadline was spent across the downtime, or whose
            # resubmission no replica accepted): the fleet record IS
            # the terminal view
            status, reason = fr.override
            return RequestOutput(
                request_id=fleet_id, prompt=fr.prompt, tokens=[],
                finished=True, finish_reason=None, ttft_s=None,
                status=status, status_reason=reason)
        out = self._handles[fr.replica].engine.result(fr.engine_rid)
        if fr.override is not None:
            status, reason = fr.override
            out = dataclasses.replace(out, finished=True, status=status,
                                      status_reason=reason)
        elif self._migrating(fr, out):
            out = dataclasses.replace(out, finished=False,
                                      finish_reason=None, status=None,
                                      status_reason=None)
        return dataclasses.replace(out, request_id=fleet_id)

    def _abort_pending_handoff(self, fleet_id: int, why: str) -> None:
        """Cancel/purge settled a request the pump still owns a pin
        for: abort its handoff so the source-side pin releases NOW, not
        at the next step."""
        rec = self._handoffs.records.get(fleet_id)
        if rec is not None:
            self._handoffs.abort(rec, why)
            self._abort_metrics(rec)

    def cancel(self, fleet_id: int) -> RequestOutput:
        """Cancel against the CURRENTLY-owning replica — after a
        failover the map already points at the new owner, so a cancel
        can never land on the stale replica's dead record.  Unknown or
        purged ids raise the same descriptive ``KeyError`` the engines
        use; cancelling an already-terminal request is idempotent.  A
        pending KV handoff is aborted (its source pin releases
        immediately)."""
        fr = self._record(fleet_id)
        if fr.replica < 0:
            return self.result(fleet_id)   # already terminal, unplaced
        self.purge_hedge(fr, "cancelled by client")
        out = self._handles[fr.replica].engine.cancel(fr.engine_rid)
        self._live.discard(fleet_id)   # settled: never fail over
        self._abort_pending_handoff(fleet_id, "cancelled by client")
        self._journal_terminal(fr, out.status, out.status_reason)
        return dataclasses.replace(out, request_id=fleet_id)

    def purge(self, fleet_id: int) -> RequestOutput:
        """``result()`` + drop every reference (router map AND the
        owning engine's record).  Long-running fleets must consume
        results this way, exactly like single engines."""
        fr = self._record(fleet_id)
        if fr.replica < 0:
            out = self.result(fleet_id)
            del self._requests[fleet_id]
            return out
        self.purge_hedge(fr, "purged by client")
        out = self._handles[fr.replica].engine.purge(fr.engine_rid)
        self._live.discard(fleet_id)
        self._abort_pending_handoff(fleet_id, "purged by client")
        self._journal_terminal(fr, out.status if fr.override is None
                               else fr.override[0],
                               out.status_reason if fr.override is None
                               else fr.override[1])
        del self._requests[fleet_id]
        if fr.override is not None:
            status, reason = fr.override
            out = dataclasses.replace(out, finished=True, status=status,
                                      status_reason=reason)
        return dataclasses.replace(out, request_id=fleet_id)

    # --------------------------------------------------------- lifecycle
    def stall_snapshot(self) -> Dict[str, object]:
        """Fleet-scope diagnostic state: every replica's
        ``EngineCore.stall_snapshot()`` plus the router's own view —
        roles, drain/retire flags, queue depth, live requests, pending
        handoffs and the autoscaler's state.  Attached to the stall
        detector's :class:`EngineStalledError`, so
        ``run_until_complete(stall_steps=)`` diagnoses wedges at fleet
        scope the way a single engine's snapshot does for one plane."""
        return {
            "queue_depth": self.queue_depth,
            "in_flight": self.in_flight,
            "live_requests": len(self._live),
            "routable_replicas": self.routable_count,
            "fleet_dead": self.fleet_dead,
            "failovers": self.metrics.c_failovers.value,
            "hedges_live": sum(1 for fr in self._requests.values()
                               if fr.hedge_rid >= 0),
            "brownout_level": self._brownout.level,
            "slow_replicas": [h.index for h in self._handles
                              if not h.retired
                              and h.engine.health.slow],
            "handoffs_pending": self._handoffs.pending,
            "handoffs": self._handoffs.snapshot(),
            "journal": None if self.journal is None
            else self.journal.position(),
            "autoscaler": None if self._autoscaler is None
            else self._autoscaler.snapshot(),
            "replicas": [
                {"index": h.index, "role": h.role,
                 "draining": h.draining, "retired": h.retired,
                 "killed": h.killed, "routed": h.routed,
                 "slow": h.engine.health.slow,
                 "step_ewma_s": round(h.step_ewma_s, 4),
                 # a killed replica's engine is a dead process: its
                 # internals are unreadable by definition, so the
                 # snapshot carries only the router-side view
                 **({} if h.killed
                    else h.engine.core.stall_snapshot())}
                for h in self._handles],
        }

    def fleet_snapshot(self) -> Dict[str, object]:
        """Back-compat alias for :meth:`stall_snapshot`."""
        return self.stall_snapshot()

    def metrics_dict(self) -> Dict[str, object]:
        """Fleet-level counters + each replica's own
        ``metrics_dict()``."""
        m = self.metrics
        return {
            "replicas": len(self._handles),
            "requests_routed": m.c_routed.value,
            "prefix_hit_tokens": m.c_hit_tokens.value,
            "failovers": m.c_failovers.value,
            "failovers_exhausted": m.c_failover_exhausted.value,
            "requests_rejected": m.c_rejected.value,
            "queue_depth": self.queue_depth,
            "roles": [h.role for h in self._handles],
            "retired_replicas": sum(1 for h in self._handles
                                    if h.retired),
            "killed_replicas": sum(1 for h in self._handles
                                   if h.killed),
            "crash_reattributed": m.c_crash_reattributed.value,
            "replay_resubmitted": m.c_replay_resubmitted.value,
            "replay_expired": m.c_replay_expired.value,
            "hedges": m.c_hedges.value,
            "hedge_wins": m.c_hedge_wins.value,
            "hedges_failed": m.c_hedge_failed.value,
            "shed_batch": m.c_shed_batch.value,
            "brownout_level": self._brownout.level,
            "slow_replicas": sum(1 for h in self._handles
                                 if not h.retired
                                 and h.engine.health.slow),
            "journal": None if self.journal is None
            else self.journal.position(),
            "handoffs_staged": m.c_handoff_staged.value,
            "handoffs_committed": m.c_handoff_committed.value,
            "handoffs_aborted": m.c_handoff_aborted.value,
            "handoff_retries": m.c_handoff_retries.value,
            "handoff_blocks_moved": m.c_handoff_blocks.value,
            "handoffs_failed_terminal": m.c_handoff_failed.value,
            "per_replica": [h.engine.metrics_dict()
                            for h in self._handles],
        }

    def accounting(self) -> Dict[str, object]:
        """The fleet total-accounting verdict (serving/fleet.py) over
        every request this router still tracks — call after a drain."""
        from . import fleet as _fleet
        return _fleet.fleet_accounting(self)

    def close(self) -> None:
        """Close every replica (idempotent, like
        :meth:`ServingEngine.close`)."""
        if self._closed:
            return
        self._closed = True
        for h in self._handles:
            h.engine.close()
