"""Fleet tier: a replica router over N serving engines.

ROADMAP direction 3's millions-of-users shape: one :class:`Router`
fronts N :class:`~paddle_tpu.serving.api.ServingEngine` replicas (each
with its own device plane / mesh slice, ideally sharing ONE obs
registry and tracer so the fleet scrapes as a single surface) and
routes every ``submit()`` on real signals:

  * **prefix affinity** — ``EngineCore.prefix_probe(prompt)`` reports
    each replica's longest radix-cached prefix WITHOUT admitting or
    pinning (a pure host walk); the router picks the replica with the
    longest hit, tie-broken by load, so shared-prefix traffic
    (system prompts, multi-turn history) keeps landing where its KV
    already lives and TTFT stays O(suffix) fleet-wide;
  * **health** — the PR-8 robustness surface is the routing input:
    replicas at ``quarantined``/``circuit_open`` are EXCLUDED,
    ``degraded`` replicas are deprioritized behind healthy ones, and a
    replica being drained (:meth:`Router.drain`) takes no new work
    while its in-flight requests finish;
  * **SLO-aware admission** — the fleet-level bounded queue
    (``max_queue`` across all replicas) and each engine's own
    submit-time backpressure (projected TTFT vs deadline, per-replica
    queue bound) gate admission; when every eligible replica rejects,
    the router re-raises :class:`RequestRejected` carrying the BEST
    replica's ``retry_after_s`` (always finite and clamped —
    serving/metrics.py).

**Failover, exactly once.**  A request that dies with a
replica-attributed terminal ``failed`` status (a quarantine casualty, a
poisoned decode row, a prefill fault) is transparently resubmitted ONCE
to the best healthy replica.  The fleet request id doubles as the
idempotency key: ``attempts`` caps total submissions at two, and the
``delivered`` high-water mark dedups the client-visible stream — the
retry regenerates tokens from position 0 (greedy / seeded-sampling
determinism makes the regenerated prefix identical), and the router
forwards only positions the client has not yet seen, so every token
position reaches the client exactly once.  Failures the CLIENT caused
(a raising stream callback) are never failed over.  ``cancel()``,
``result()``, ``stream()`` and ``purge()`` always resolve through the
router's authoritative fleet-id -> (replica, engine-id) map, so they
follow the request across a failover.

The router is pure host-side control plane: it never touches a device
array and adds zero work to any engine's hot step loop.  Replicas
should be built with ``fault_tolerance=FaultToleranceConfig(...)`` —
the watchdog's containment is what turns a replica fault into the
terminal ``failed`` status the failover scan routes on; without it a
step exception propagates out of :meth:`Router.step` to the caller.

Fleet accounting (chaos invariant) lives in ``serving/fleet.py``;
``scripts/fleet_chaos_smoke.py`` drives one injected replica fault
end-to-end and ``tests/test_zz_fleet_serving.py`` pins the invariant.
See docs/serving.md "Fleet tier".
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .api import RequestOutput, ServingEngine
from .errors import EngineStalledError, RequestRejected
from .health import CIRCUIT_OPEN, DEGRADED, QUARANTINED
from .scheduler import SamplingParams

__all__ = ["Router", "ReplicaHandle"]

# terminal reasons a failover must never retry: the failure is
# attributed to the CLIENT's sink, not the replica — a resubmission
# would re-raise into the same callback and burn the retry for nothing
_CLIENT_FAULT_PREFIX = "stream callback"


class ReplicaHandle:
    """Router-side view of one replica: the engine plus the routing
    state the router owns about it (drain flag, routed count)."""

    __slots__ = ("index", "engine", "draining", "routed")

    def __init__(self, index: int, engine: ServingEngine):
        self.index = index
        self.engine = engine
        self.draining = False
        self.routed = 0          # fleet requests ever routed here

    @property
    def load(self) -> int:
        """Queued + placed requests — the affinity tie-breaker."""
        core = self.engine.core
        return core.scheduler.queue_depth + core.scheduler.active

    def __repr__(self) -> str:
        return (f"ReplicaHandle({self.index}, "
                f"health={self.engine.health.state!r}, "
                f"draining={self.draining}, load={self.load})")


class _FleetRequest:
    """One client-visible request's routing record.  ``fleet_id`` is
    the idempotency key: ``attempts`` caps submissions at two (original
    + one failover) and ``delivered`` is the exactly-once high-water
    mark for the client stream."""

    __slots__ = ("fleet_id", "prompt", "max_new_tokens", "sampling",
                 "eos_token_id", "client_stream", "deadline_s",
                 "ttft_deadline_s", "submit_time", "replica",
                 "engine_rid", "attempts", "delivered", "history")

    def __init__(self, fleet_id: int, prompt: np.ndarray,
                 max_new_tokens: int, sampling, eos_token_id,
                 client_stream, deadline_s, ttft_deadline_s):
        self.fleet_id = fleet_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.eos_token_id = eos_token_id
        self.client_stream = client_stream
        self.deadline_s = deadline_s
        self.ttft_deadline_s = ttft_deadline_s
        self.submit_time = 0.0        # perf_counter at FIRST submission
        self.replica = -1             # current owner (authoritative)
        self.engine_rid = -1
        self.attempts = 0
        self.delivered = 0            # client-visible token positions
        # (replica, engine_rid, status_reason) per surrendered attempt
        self.history: List[Tuple[int, int, str]] = []


class _RouterMetrics:
    """The router's obs instruments, bound get-or-create into the
    (usually shared) registry — glossary rows in docs/observability.md."""

    def __init__(self, registry, tracer):
        self.registry = registry
        self.tracer = tracer
        self.lane = tracer.claim_lane_block()
        tracer.set_lane_name(self.lane, "serving.router", pin=True)
        g, c = registry.gauge, registry.counter
        self.g_replicas = g("router.replicas",
                            "replicas fronted by this router")
        self.g_healthy = g("router.healthy_replicas",
                           "replicas currently routable (healthy or "
                           "degraded, not draining)")
        self.g_draining = g("router.draining_replicas",
                            "replicas draining (no new admissions)")
        self.g_queue = g("router.queue_depth",
                         "fleet-wide waiting requests at the last step")
        self.c_routed = c("router.requests_routed",
                          "fleet submissions accepted and routed")
        self.c_hit_tokens = c("router.prefix_hit_tokens",
                              "prompt tokens the routed replica's radix "
                              "cache already held at routing time")
        self.c_failovers = c("router.failovers",
                             "requests resubmitted to a healthy replica "
                             "after a replica-attributed failure")
        self.c_failover_exhausted = c(
            "router.failovers_exhausted",
            "replica-attributed failures that could NOT fail over "
            "(retry spent, deadline blown, or no replica accepted)")
        self.c_rejected = c("router.requests_rejected",
                            "fleet submissions refused (no healthy "
                            "replica / fleet queue / every replica "
                            "rejected)")

    def on_route(self, fleet_id: int, replica: int, hit_tokens: int) -> None:
        self.c_routed.inc()
        if hit_tokens > 0:
            self.c_hit_tokens.inc(hit_tokens)

    def on_failover(self, fleet_id: int, src: int, dst: int,
                    reason: str) -> None:
        self.c_failovers.inc()
        self.tracer.event("failover", lane=self.lane, fleet_id=fleet_id,
                          from_replica=src, to_replica=dst,
                          reason=str(reason)[:200])

    def on_failover_exhausted(self, fleet_id: int, replica: int,
                              why: str) -> None:
        self.c_failover_exhausted.inc()
        self.tracer.event("failover_exhausted", lane=self.lane,
                          fleet_id=fleet_id, replica=replica,
                          reason=str(why)[:200])

    def on_reject(self, reason: str) -> None:
        self.c_rejected.inc()
        self.tracer.event("router_reject", lane=self.lane, reason=reason)

    def on_drain(self, replica: int, phase: str) -> None:
        self.tracer.event(phase, lane=self.lane, replica=replica)

    def publish(self, handles: Sequence[ReplicaHandle]) -> None:
        self.g_replicas.set(len(handles))
        healthy = sum(1 for h in handles if not h.draining
                      and h.engine.health.state
                      not in (QUARANTINED, CIRCUIT_OPEN))
        self.g_healthy.set(healthy)
        self.g_draining.set(sum(1 for h in handles if h.draining))
        self.g_queue.set(sum(h.engine.core.scheduler.queue_depth
                             for h in handles))


class Router:
    """Prefix-affinity, health-aware request router over N serving
    replicas — the fleet tier (docs/serving.md "Fleet tier").

    ``replicas`` are pre-built :class:`ServingEngine` instances (build
    them onto ONE shared registry/tracer for a single scrape surface —
    :meth:`Router.build` does exactly that).  The router owns the
    fleet-id namespace: every id handed out by :meth:`submit` resolves
    through the authoritative request -> replica map, across failovers.

    ``max_queue`` bounds the FLEET queue (sum of replica queue depths);
    per-replica bounds/SLO checks still apply at each engine.
    ``failover=False`` disables resubmission (replica failures surface
    as terminal ``failed``); ``affinity=False`` degrades routing to
    round-robin over the eligible replicas — the measured baseline the
    prefix-affinity win is pinned against.
    """

    def __init__(self, replicas: Sequence[ServingEngine], *,
                 max_queue: Optional[int] = None,
                 failover: bool = True,
                 affinity: bool = True,
                 registry=None, tracer=None):
        if not replicas:
            raise ValueError("Router needs at least one replica engine")
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None)")
        self._handles = [ReplicaHandle(i, eng)
                         for i, eng in enumerate(replicas)]
        self.max_queue = max_queue
        self.failover = failover
        self.affinity = affinity
        self.registry = registry if registry is not None \
            else replicas[0].registry
        self.tracer = tracer if tracer is not None \
            else replicas[0].tracer
        self.metrics = _RouterMetrics(self.registry, self.tracer)
        self._requests: Dict[int, _FleetRequest] = {}
        self._live: set = set()       # fleet ids the failover scan owns
        self._ids = itertools.count()
        self._rr = 0                  # round-robin cursor (affinity off)
        self._closed = False
        self.metrics.publish(self._handles)

    @classmethod
    def build(cls, model_factory: Callable, replicas: int = 2, *,
              registry=None, tracer=None, max_queue: Optional[int] = None,
              failover: bool = True, affinity: bool = True,
              **engine_kw) -> "Router":
        """Construct ``replicas`` engines onto ONE shared registry and
        tracer (fresh ones when not given) and front them with a router.
        ``model_factory()`` is called once per replica — return the same
        weights (e.g. re-seed inside the factory) when fleet-wide token
        parity matters; ``engine_kw`` is forwarded to every
        :class:`ServingEngine`."""
        from ..obs import MetricsRegistry, Tracer
        registry = registry if registry is not None else MetricsRegistry()
        tracer = tracer if tracer is not None else Tracer()
        engines = [ServingEngine(model_factory(), registry=registry,
                                 tracer=tracer, **engine_kw)
                   for _ in range(replicas)]
        return cls(engines, max_queue=max_queue, failover=failover,
                   affinity=affinity, registry=registry, tracer=tracer)

    # ---------------------------------------------------------- topology
    @property
    def replicas(self) -> Tuple[ReplicaHandle, ...]:
        return tuple(self._handles)

    @property
    def queue_depth(self) -> int:
        """Fleet-wide waiting requests (the ``max_queue`` bound)."""
        return sum(h.engine.core.scheduler.queue_depth
                   for h in self._handles)

    @property
    def in_flight(self) -> int:
        """Queued + placed requests across the fleet."""
        return sum(h.load for h in self._handles)

    def _handle(self, replica: int) -> ReplicaHandle:
        if not 0 <= replica < len(self._handles):
            raise KeyError(
                f"unknown replica index {replica} — this router fronts "
                f"{len(self._handles)} replicas")
        return self._handles[replica]

    def _eligible(self) -> List[ReplicaHandle]:
        """Replicas new work may be routed to: not draining, not
        quarantined, circuit not open (degraded stays eligible — it is
        deprioritized by the route order, not excluded)."""
        return [h for h in self._handles
                if not h.draining
                and h.engine.health.state not in (QUARANTINED,
                                                  CIRCUIT_OPEN)]

    def _route_order(self, eligible: List[ReplicaHandle],
                     prompt: np.ndarray
                     ) -> List[Tuple[ReplicaHandle, Optional[int]]]:
        """The replica try-order for one prompt, best first, with each
        candidate's probed prefix-hit length.  Affinity mode: longest
        cached prefix wins, healthy beats degraded, load breaks ties.
        Round-robin mode: rotate the cursor without probing anyone
        (hit = None; the caller probes only the ACCEPTED replica so
        ``router.prefix_hit_tokens`` stays comparable between the two
        policies without N radix walks per submit)."""
        if not self.affinity:
            k = self._rr % len(eligible)
            self._rr += 1
            rotated = eligible[k:] + eligible[:k]
            return [(h, None) for h in rotated]
        probes = [(h, h.engine.core.prefix_probe(prompt))
                  for h in eligible]
        return sorted(
            probes,
            key=lambda p: (p[0].engine.health.state == DEGRADED,
                           -p[1], p[0].load, p[0].index))

    # -------------------------------------------------------- submission
    def submit(self, prompt, max_new_tokens: int = 16,
               sampling: Optional[SamplingParams] = None,
               eos_token_id: Optional[int] = None,
               stream: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None) -> int:
        """Route one request; returns its FLEET id (valid with
        :meth:`result`/:meth:`cancel`/:meth:`stream`/:meth:`purge` on
        this router — engine-local ids never leak to clients).

        Raises :class:`RequestRejected` when no replica can take the
        request: ``no_healthy_replica`` (every replica excluded by
        health or drain), ``fleet_queue_full`` (the fleet-wide
        ``max_queue`` bound), or the best replica's own rejection
        (``queue_full`` / ``slo_unattainable`` / ``circuit_open``) when
        every eligible replica refused — always carrying the best
        available ``retry_after_s`` hint.  Validation ``ValueError``\\ s
        (empty prompt, prompt+new > max_seq, bad sampling) propagate
        from the first replica tried, before any state is recorded."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        fleet_id = next(self._ids)
        eligible = self._eligible()
        if not eligible:
            # hint only from replicas that can plausibly recover — a
            # circuit-open replica never will (engine.check_admission
            # hints None for the same reason), so an all-circuit-open
            # fleet correctly reports "no hint" instead of telling
            # clients to retry against the dead replicas' stale history
            self._reject(fleet_id, prompt, "no_healthy_replica",
                         self._best_hint(
                             [h for h in self._handles
                              if h.engine.health.state != CIRCUIT_OPEN]))
        if self.max_queue is not None \
                and self.queue_depth >= self.max_queue:
            self._reject(fleet_id, prompt, "fleet_queue_full",
                         self._best_hint(eligible))
        order = self._route_order(eligible, prompt)
        fr = _FleetRequest(fleet_id, prompt, max_new_tokens, sampling,
                           eos_token_id, stream, deadline_s,
                           ttft_deadline_s)
        fr.submit_time = time.perf_counter()
        rejections: List[RequestRejected] = []
        for h, hit in order:
            try:
                rid = self._submit_to(h, fr)
            except RequestRejected as e:
                rejections.append(e)
                continue
            fr.replica, fr.engine_rid = h.index, rid
            fr.attempts = 1
            h.routed += 1
            self._requests[fleet_id] = fr
            self._live.add(fleet_id)
            if hit is None:         # round-robin: probe the winner only
                hit = h.engine.core.prefix_probe(prompt)
            self.metrics.on_route(fleet_id, h.index, hit)
            return fleet_id
        # every eligible replica rejected: surface the BEST replica's
        # reason with the best (smallest, still-finite) retry hint
        hints = [e.retry_after_s for e in rejections
                 if e.retry_after_s is not None]
        self._reject(fleet_id, prompt, rejections[0].reason,
                     min(hints) if hints else None)

    def _reject(self, fleet_id: int, prompt: np.ndarray, reason: str,
                retry_after_s: Optional[float]):
        self.metrics.on_reject(reason)
        out = RequestOutput(
            request_id=fleet_id, prompt=prompt, tokens=[], finished=True,
            finish_reason=None, ttft_s=None, status="rejected",
            status_reason=reason)
        raise RequestRejected(reason, retry_after_s, output=out)

    def _best_hint(self, handles: Sequence[ReplicaHandle]
                   ) -> Optional[float]:
        hints = [h.engine.metrics.retry_after_hint() for h in handles]
        hints = [x for x in hints if x is not None]
        return min(hints) if hints else None

    def _submit_to(self, h: ReplicaHandle, fr: _FleetRequest,
                   now: Optional[float] = None) -> int:
        """Submit (or RE-submit, on failover) one fleet request to a
        replica, with the deadline budgets shrunk by the time already
        spent — a failover must not silently grant a fresh deadline.  A
        request whose first token was already delivered carries no TTFT
        deadline into the retry (the client's TTFT was met)."""
        if now is None:
            now = time.perf_counter()
        elapsed = max(now - fr.submit_time, 0.0)
        deadline = fr.deadline_s
        if deadline is not None:
            deadline = max(deadline - elapsed, 0.0)
        ttft = fr.ttft_deadline_s
        if ttft is not None:
            ttft = None if fr.delivered > 0 \
                else max(ttft - elapsed, 0.0)
        return h.engine.submit(
            fr.prompt, max_new_tokens=fr.max_new_tokens,
            sampling=fr.sampling, eos_token_id=fr.eos_token_id,
            stream=self._fleet_stream(fr),
            deadline_s=deadline, ttft_deadline_s=ttft)

    def _fleet_stream(self, fr: _FleetRequest) -> Callable:
        """The exactly-once dedup wrapper: every replica attempt streams
        through it; positions below the delivered high-water mark (a
        failover retry regenerating the prefix it already served) are
        swallowed, so the client sees each token position once."""
        def cb(req, tok):
            pos = len(req.tokens) - 1   # _emit appends before calling
            if pos < fr.delivered:
                return
            fr.delivered = pos + 1
            if fr.client_stream is not None:
                fr.client_stream(req, tok)
        return cb

    # --------------------------------------------------------- execution
    def step(self) -> int:
        """One fleet iteration: step every replica, then run the
        failover scan over live requests and refresh the fleet gauges.
        Returns the number of requests still in flight fleet-wide."""
        for h in self._handles:
            h.engine.step()
        self._scan_failover()
        self.metrics.publish(self._handles)
        return self.in_flight

    def has_work(self) -> bool:
        return any(h.engine.core.scheduler.has_work()
                   for h in self._handles)

    def _progress(self) -> int:
        return (sum(h.engine.core.progress_counter
                    for h in self._handles)
                + self.metrics.c_failovers.value
                + self.metrics.c_failover_exhausted.value)

    def run_until_complete(self, max_steps: Optional[int] = None,
                           stall_steps: Optional[int] = 64) -> int:
        """Step until every replica drains; returns steps taken.  The
        stall detector watches FLEET progress (token emits, admissions,
        dispositions, failovers) so a wedged replica raises
        :class:`EngineStalledError` with a per-replica snapshot instead
        of spinning."""
        steps = stalled = 0
        last = self._progress()
        while self.has_work():
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain within {max_steps} steps")
            self.step()
            steps += 1
            p = self._progress()
            if p != last:
                last, stalled = p, 0
            else:
                stalled += 1
                if stall_steps is not None and stalled >= stall_steps \
                        and self.has_work():
                    raise EngineStalledError(stalled,
                                             self.fleet_snapshot())
        return steps

    def stream(self, fleet_id: int) -> Iterator[int]:
        """Yield the request's tokens as they are generated, stepping
        the FLEET while waiting — so health scans and failovers keep
        running; the iterator transparently follows the request onto a
        failover target (the dedup wrapper guarantees each yielded
        position was generated for this client exactly once)."""
        fr = self._record(fleet_id)
        seen = 0
        while True:
            req = self._handles[fr.replica].engine._requests.get(
                fr.engine_rid)
            toks = req.tokens if req is not None else []
            while seen < len(toks):
                yield toks[seen]
                seen += 1
            if fleet_id not in self._live:
                return
            self.step()

    # ---------------------------------------------------------- failover
    def _scan_failover(self) -> None:
        """Settle finished fleet requests; resubmit replica-attributed
        failures ONCE to the best healthy replica.  Runs after every
        fleet step, off any engine's hot path."""
        if not self._live:
            return
        for fid in list(self._live):
            fr = self._requests[fid]
            # the engine-internal record is authoritative and cheap;
            # result() would build a RequestOutput copy per scan
            req = self._handles[fr.replica].engine._requests.get(
                fr.engine_rid)
            if req is None or not req.finished:
                continue
            if (self.failover and req.status == "failed"
                    and fr.attempts < 2
                    and not str(req.status_reason or "").startswith(
                        _CLIENT_FAULT_PREFIX)):
                if self._try_failover(fr, req):
                    continue        # re-owned: stays live on the target
            self._live.discard(fid)

    def _try_failover(self, fr: _FleetRequest, failed_req) -> bool:
        """Resubmit one failed fleet request.  Returns True when a
        healthy replica accepted it (the router map now points there);
        False leaves the terminal ``failed`` standing."""
        now = time.perf_counter()
        if fr.deadline_s is not None \
                and now - fr.submit_time >= fr.deadline_s:
            self.metrics.on_failover_exhausted(
                fr.fleet_id, fr.replica, "deadline already spent")
            return False
        # prefer a DIFFERENT replica; fall back to the (recovered)
        # origin only when it is the sole eligible one
        eligible = self._eligible()
        targets = [h for h in eligible if h.index != fr.replica] \
            or eligible
        if not targets:
            self.metrics.on_failover_exhausted(
                fr.fleet_id, fr.replica, "no healthy replica")
            return False
        src, src_rid = fr.replica, fr.engine_rid
        reason = failed_req.status_reason or "failed"
        for h, hit in self._route_order(targets, fr.prompt):
            try:
                rid = self._submit_to(h, fr, now=now)
            except RequestRejected:
                continue
            # drop the surrendered attempt's record from the old engine
            # (terminal — purge only releases the host-side reference)
            fr.history.append((src, src_rid, reason))
            self._handles[src].engine.purge(src_rid)
            fr.replica, fr.engine_rid = h.index, rid
            fr.attempts += 1
            h.routed += 1
            self.metrics.on_failover(fr.fleet_id, src, h.index, reason)
            return True
        self.metrics.on_failover_exhausted(
            fr.fleet_id, fr.replica, "every healthy replica rejected")
        return False

    # ------------------------------------------------------------ drains
    def drain(self, replica: int) -> None:
        """Stop routing NEW work to ``replica`` (index) while its
        in-flight requests finish normally — the graceful half of
        taking a replica out of rotation.  Balance with
        :meth:`undrain` (a registered graftlint ``ResourcePair``): a
        drain leaked on an exception path silently shrinks the fleet."""
        h = self._handle(replica)
        h.draining = True
        self.metrics.on_drain(replica, "drain")
        self.metrics.publish(self._handles)

    def undrain(self, replica: int) -> None:
        """Return a drained replica to the routing rotation
        (idempotent)."""
        h = self._handle(replica)
        h.draining = False
        self.metrics.on_drain(replica, "undrain")
        self.metrics.publish(self._handles)

    def drained(self, replica: int) -> bool:
        """True once a draining replica has no queued or in-flight
        work left — safe to rebuild/retire."""
        h = self._handle(replica)
        return h.draining and not h.engine.core.scheduler.has_work()

    # ----------------------------------------------------------- results
    def _record(self, fleet_id: int) -> _FleetRequest:
        fr = self._requests.get(fleet_id)
        if fr is None:
            raise KeyError(
                f"unknown fleet request_id {fleet_id} — never submitted "
                f"to this router, or already purged")
        return fr

    def result(self, fleet_id: int) -> RequestOutput:
        """The request's current view FROM ITS OWNING REPLICA (the map
        is authoritative across failovers), re-keyed to the fleet id."""
        fr = self._record(fleet_id)
        out = self._handles[fr.replica].engine.result(fr.engine_rid)
        return dataclasses.replace(out, request_id=fleet_id)

    def cancel(self, fleet_id: int) -> RequestOutput:
        """Cancel against the CURRENTLY-owning replica — after a
        failover the map already points at the new owner, so a cancel
        can never land on the stale replica's dead record.  Unknown or
        purged ids raise the same descriptive ``KeyError`` the engines
        use; cancelling an already-terminal request is idempotent."""
        fr = self._record(fleet_id)
        out = self._handles[fr.replica].engine.cancel(fr.engine_rid)
        self._live.discard(fleet_id)   # settled: never fail over
        return dataclasses.replace(out, request_id=fleet_id)

    def purge(self, fleet_id: int) -> RequestOutput:
        """``result()`` + drop every reference (router map AND the
        owning engine's record).  Long-running fleets must consume
        results this way, exactly like single engines."""
        fr = self._record(fleet_id)
        out = self._handles[fr.replica].engine.purge(fr.engine_rid)
        self._live.discard(fleet_id)
        del self._requests[fleet_id]
        return dataclasses.replace(out, request_id=fleet_id)

    # --------------------------------------------------------- lifecycle
    def fleet_snapshot(self) -> Dict[str, object]:
        """Per-replica diagnostic state (attached to the stall
        detector's :class:`EngineStalledError`)."""
        return {
            "replicas": [
                {"index": h.index, "draining": h.draining,
                 "routed": h.routed,
                 **h.engine.core.stall_snapshot()}
                for h in self._handles],
            "live_requests": len(self._live),
            "failovers": self.metrics.c_failovers.value,
        }

    def metrics_dict(self) -> Dict[str, object]:
        """Fleet-level counters + each replica's own
        ``metrics_dict()``."""
        return {
            "replicas": len(self._handles),
            "requests_routed": self.metrics.c_routed.value,
            "prefix_hit_tokens": self.metrics.c_hit_tokens.value,
            "failovers": self.metrics.c_failovers.value,
            "failovers_exhausted":
                self.metrics.c_failover_exhausted.value,
            "requests_rejected": self.metrics.c_rejected.value,
            "queue_depth": self.queue_depth,
            "per_replica": [h.engine.metrics_dict()
                            for h in self._handles],
        }

    def accounting(self) -> Dict[str, object]:
        """The fleet total-accounting verdict (serving/fleet.py) over
        every request this router still tracks — call after a drain."""
        from . import fleet as _fleet
        return _fleet.fleet_accounting(self)

    def close(self) -> None:
        """Close every replica (idempotent, like
        :meth:`ServingEngine.close`)."""
        if self._closed:
            return
        self._closed = True
        for h in self._handles:
            h.engine.close()
