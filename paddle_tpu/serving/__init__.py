"""TPU-native continuous-batching serving engine (pure JAX, fixed shapes).

Reference analog: the serving stack the reference feeds through
fused_multi_transformer — PaddleNLP's predictor loop batching concurrent
generation requests over one shared decoder.  Here the same capability is
built TPU-natively: a slot-pooled KV cache + shared-prefix block pool
(kv_pool), a radix tree reusing cached prefixes across requests
(prefix_cache), FCFS admission with pow2 prefill buckets, chunked
prefill and a bounded head-of-line skip (scheduler), one compiled
fixed-shape decode step with per-slot sampling (engine), a
submit/step/stream surface (api), off-hot-path telemetry — metrics
registry + request-lifecycle tracing via paddle_tpu.obs (metrics) —
a durable request journal for crash-consistent fleets (journal), a
manifest-driven AOT program store for zero-cold-start engines (aot),
and speculative decoding — host-side per-slot n-gram drafts checked by
ONE batched fixed-shape verify program (spec).
See docs/serving.md and docs/observability.md.
"""

from .aot import (AOTStore, AOTStoreError, AOTStoreWriter,
                  aot_fingerprint, build_engine_store,
                  engine_aot_context)
from .api import Request, RequestOutput, SamplingParams, ServingEngine
from .autoscaler import Autoscaler
from .engine import EngineCore, finite_or_sentinel, sample_rows
from .errors import EngineStalledError, RequestRejected
from .faults import FaultError, FaultInjector
from .fleet import fleet_accounting, replica_accounting
from .handoff import Handoff, HandoffManager
from .health import (DegradationLadder, EngineHealth,
                     FaultToleranceConfig)
from .journal import Journal, JournalError
from .kv_pool import BlockPool, KVPool
from .metrics import ServingMetrics
from .prefix_cache import MatchResult, PrefixCache
from .router import ReplicaHandle, Router
from .scheduler import PRIORITIES, Scheduler, bucket_length
from .spec import NGramDraftTable

__all__ = ["ServingEngine", "Request", "RequestOutput", "SamplingParams",
           "EngineCore", "sample_rows", "finite_or_sentinel", "KVPool",
           "BlockPool", "PrefixCache", "MatchResult", "ServingMetrics",
           "Scheduler", "bucket_length",
           # fault-tolerance surface (docs/serving.md "Fault tolerance")
           "FaultToleranceConfig", "EngineHealth", "DegradationLadder",
           "FaultInjector", "FaultError", "RequestRejected",
           "EngineStalledError",
           # fleet tier (docs/serving.md "Fleet tier")
           "Router", "ReplicaHandle", "fleet_accounting",
           "replica_accounting",
           # disaggregated fleet (docs/serving.md "Disaggregated fleet")
           "Autoscaler", "Handoff", "HandoffManager",
           # crash consistency (docs/serving.md "Crash recovery")
           "Journal", "JournalError",
           # tail latency (docs/serving.md "Tail latency")
           "PRIORITIES",
           # zero cold start (docs/serving.md "Zero cold start")
           "AOTStore", "AOTStoreWriter", "AOTStoreError",
           "build_engine_store", "engine_aot_context",
           "aot_fingerprint",
           # speculative decoding (docs/serving.md "Speculative
           # decoding")
           "NGramDraftTable"]
