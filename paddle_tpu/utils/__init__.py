"""paddle.utils parity — run_check, deprecated, try_import, unique_name.

Reference: python/paddle/utils/ — install self-check (run_check spins a
tiny train step on the available device), deprecation decorator, lazy
imports, unique name generator.
"""

from __future__ import annotations

import functools
import importlib
import warnings
from typing import Optional

from . import dlpack  # noqa: F401

__all__ = ["run_check", "deprecated", "try_import", "unique_name",
           "dlpack"]


def run_check():
    """Reference: paddle.utils.run_check — verify the install end to end
    (one tiny jitted train step on the default backend) and report."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..nn.functional_call import functional_call, state
    from .. import nn
    from .. import optimizer as opt

    devs = jax.devices()
    model = nn.Linear(4, 2)
    params, buffers = state(model)
    o = opt.SGD(learning_rate=0.1)
    ostate = o.init(params)
    x = jnp.asarray(np.ones((2, 4), np.float32))

    @jax.jit
    def step(p, os_):
        def lf(p):
            out, _ = functional_call(model, p, buffers, (x,))
            return jnp.mean(out ** 2)
        l, g = jax.value_and_grad(lf)(p)
        newp, nos = o.update(g, os_, p)
        return newp, nos, l

    params, ostate, loss = step(params, ostate)
    float(loss)
    print(f"PaddleTPU works well on {len(devs)} {devs[0].platform} "
          f"device(s).")
    print("PaddleTPU is installed successfully!")
    return True


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 1):
    """Decorator parity: warns on call (level>=2 raises)."""
    def deco(fn):
        msg = (f"API '{fn.__module__}.{fn.__name__}' is deprecated since "
               f"{since or 'this release'}"
               + (f", use '{update_to}' instead" if update_to else "")
               + (f". Reason: {reason}" if reason else "."))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def try_import(module_name: str, err_msg: Optional[str] = None):
    """Reference: paddle.utils.try_import — import or raise with guidance."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"module {module_name!r} is required but not "
                       f"installed (pip install {module_name})")


class _UniqueName:
    """paddle.utils.unique_name namespace: generate/guard/switch."""

    def __init__(self):
        self._counters = {}
        self._prefix = ""

    def generate(self, key: str) -> str:
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{self._prefix}{key}_{n}"

    def switch(self, new_generator=None):
        """Install ``new_generator`` (a counter state from a previous
        switch; fresh when None) and return the previous state — the
        paddle round-trip ``old = switch(); ...; switch(old)`` restores."""
        old = self._counters
        self._counters = dict(new_generator) if new_generator is not None \
            else {}
        return old

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def _g():
            old = self.switch(new_generator)
            try:
                yield
            finally:
                self.switch(old)
        return _g()


unique_name = _UniqueName()

from . import cpp_extension  # noqa: F401


def require_version(min_version: str, max_version: str = None):
    """Reference: paddle.utils.require_version — assert the installed
    framework version is inside [min_version, max_version].  Raises
    ValueError on malformed inputs and RuntimeError (not ImportError —
    the reference's choice) on mismatch."""
    from .. import __version__

    def parse(v, what):
        if not isinstance(v, str) or not v:
            raise ValueError(f"{what} must be a non-empty str, got {v!r}")
        parts = v.split(".")
        if not all(p.isdigit() for p in parts):
            raise ValueError(f"{what} {v!r} is not a dotted integer version")
        return tuple(int(p) for p in parts)

    cur = parse(__version__, "installed version")
    lo = parse(min_version, "min_version")

    def pad(a, b):
        # zero-pad to equal length (reference semantics: "0.2" == "0.2.0")
        n = max(len(a), len(b))
        return a + (0,) * (n - len(a)), b + (0,) * (n - len(b))

    cur_lo, lo = pad(cur, lo)
    if cur_lo < lo:
        raise RuntimeError(
            f"installed version {__version__} < required min_version "
            f"{min_version}")
    if max_version is not None:
        hi = parse(max_version, "max_version")
        cur_hi, hi = pad(cur, hi)
        if cur_hi > hi:
            raise RuntimeError(
                f"installed version {__version__} > allowed max_version "
                f"{max_version}")


class _LegacyProfilerModule:
    """paddle.utils.profiler parity (the legacy profiler entry points,
    python/paddle/utils/profiler.py) — thin aliases over
    paddle_tpu.profiler."""

    @staticmethod
    def start_profiler(state="All", tracer_option="Default"):
        from .. import profiler as P
        prof = P.Profiler()
        prof.start()
        _LegacyProfilerModule._active = prof
        return prof

    @staticmethod
    def stop_profiler(sorted_key=None, profile_path=None):
        prof = getattr(_LegacyProfilerModule, "_active", None)
        if prof is not None:
            prof.stop()
            if profile_path:
                prof.export(profile_path)
            _LegacyProfilerModule._active = None


profiler = _LegacyProfilerModule()

__all__ += ["require_version", "profiler"]
