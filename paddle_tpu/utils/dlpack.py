"""paddle.utils.dlpack — zero-copy tensor exchange via the DLPack
protocol (reference: python/paddle/utils/dlpack.py to_dlpack/from_dlpack).

TPU note: DLPack exchange is a HOST-memory protocol here — jax arrays on
CPU export/import without copying; arrays living on a TPU device are
transferred to host by jax before export (the reference's GPU path has
the same device-boundary caveat with non-CUDA consumers).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a tensor as a DLPack capsule consumable by torch/numpy/
    cupy (``torch.utils.dlpack.from_dlpack`` etc.)."""
    x = jnp.asarray(x)
    try:
        if any(d.platform != "cpu" for d in x.devices()):
            # jax only exports CPU/GPU buffers over DLPack: bring
            # TPU-resident arrays to host first (docstring contract)
            import numpy as np
            return np.asarray(jax.device_get(x)).__dlpack__()
    except AttributeError:
        pass  # tracers/non-committed values: fall through
    return x.__dlpack__()


def from_dlpack(dlpack):
    """Import a DLPack capsule OR any object implementing
    ``__dlpack__`` (torch tensors, numpy arrays) as a jax array."""
    return jax.dlpack.from_dlpack(dlpack)
