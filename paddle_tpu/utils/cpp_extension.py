"""C++ custom-op extensions (reference: python/paddle/utils/
cpp_extension/ — CppExtension + load() JIT-compiling user C++ into
loadable custom operators).

TPU-native design: the op's C++ runs on the HOST (there is no user CUDA
on TPU; device compute belongs to XLA/Pallas).  ``load`` compiles the
sources with g++ into a shared library (same lazy-build pattern as the
native DataLoader ring, paddle_tpu/lib/shm_ring.cpp) and binds exported
functions through ctypes.  ``custom_op`` wraps an exported function as a
JAX-callable that WORKS UNDER JIT via ``jax.pure_callback`` — the
reference's "custom op usable inside the compiled program" contract, with
the host round-trip as the documented cost.

Exported C ABI (documented convention, replacing the reference's
PD_BUILD_OP macro machinery): each op is

    extern "C" void <name>(const float* in, float* out, int64_t n);

elementwise over ``n`` floats (in and out may have the same length), or
any richer signature the caller binds manually via ``lib.fn``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["load", "custom_op", "CppExtension"]


class CppExtension:
    """Build-spec carrier (reference signature parity; ``setup(ext_modules=
    [CppExtension(...)])`` maps onto load())."""

    def __init__(self, sources: Sequence[str], extra_compile_args=None,
                 include_dirs=None, name: Optional[str] = None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.include_dirs = list(include_dirs or [])
        self.name = name


class _Loaded:
    def __init__(self, name: str, lib: ctypes.CDLL, path: str):
        self.name = name
        self._lib = lib
        self.lib_path = path

    def __getattr__(self, fn_name):
        return getattr(self._lib, fn_name)


def load(name: str, sources: Sequence[str], extra_cflags=None,
         extra_include_paths=None, build_directory: Optional[str] = None,
         verbose: bool = False) -> _Loaded:
    """Compile ``sources`` (paths to .cc/.cpp files) into ``lib<name>.so``
    and load it (reference: cpp_extension.load)."""
    # per-user private build dir: the artifact is dlopen'd, so a shared
    # world-writable location would let another local user pre-plant a
    # library at the predictable path
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_cpp_ext_{os.getuid()}")
    os.makedirs(build_dir, exist_ok=True)
    try:
        os.chmod(build_dir, 0o700)
    except OSError:
        pass
    srcs = [os.path.abspath(s) for s in sources]
    # content-hashed artifact name: dlopen caches by PATH within a
    # process, so rebuilding in place would silently keep executing the
    # OLD image — changed sources OR build flags must map to a fresh
    # .so path
    import hashlib
    h = hashlib.sha256()
    for s in srcs:
        h.update(s.encode() + b"\0")
        with open(s, "rb") as f:
            h.update(f.read())
        h.update(b"\0")
    for flag in (extra_cflags or []):
        h.update(flag.encode() + b"\0")
    for inc in (extra_include_paths or []):
        h.update(inc.encode() + b"\0")
    so_path = os.path.join(build_dir,
                           f"lib{name}_{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        # compile to a unique temp name, rename atomically: concurrent
        # loaders must never dlopen a half-written artifact
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               "-o", tmp_path, *srcs]
        for inc in (extra_include_paths or []):
            cmd.append(f"-I{inc}")
        cmd.extend(extra_cflags or [])
        if verbose:
            print("cpp_extension:", " ".join(cmd))
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{r.stderr[-2000:]}")
        os.replace(tmp_path, so_path)
    return _Loaded(name, ctypes.CDLL(so_path), so_path)


def custom_op(loaded: _Loaded, fn_name: str) -> Callable:
    """Bind exported ``void fn(const float*, float*, int64_t)`` as a
    jit-compatible JAX callable (host callback; float32 elementwise
    contract — see module docstring)."""
    import jax
    import jax.numpy as jnp

    cfn = getattr(loaded, fn_name)
    cfn.restype = None
    cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

    def host(x):
        x = np.ascontiguousarray(np.asarray(x, np.float32))
        out = np.empty_like(x)
        cfn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(x.size))
        return out

    def apply(x):
        x = jnp.asarray(x, jnp.float32)
        return jax.pure_callback(
            host, jax.ShapeDtypeStruct(x.shape, jnp.float32), x,
            vmap_method="sequential")

    apply.__name__ = fn_name
    return apply
