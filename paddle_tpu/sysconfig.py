"""paddle.sysconfig parity — header/library paths for extension builds.

Reference: python/paddle/sysconfig.py — get_include()/get_lib() feed
custom-op build scripts.  Here the native pieces live in
``paddle_tpu/lib`` (C++ TCPStore server, shm ring); there are no C++
headers to compile against (the extension seam is
paddle_tpu.device.register_custom_device + ctypes), so get_include
returns the package's include dir, creating the convention even while
empty.
"""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    return os.path.join(_PKG, "include")


def get_lib() -> str:
    return os.path.join(_PKG, "lib")
