"""Metrics (reference: python/paddle/metric/metrics.py — Metric, Accuracy,
Precision, Recall, Auc)."""

from __future__ import annotations

import numpy as np

__all__ = ["accuracy", "Metric", "Accuracy", "Precision", "Recall", "Auc"]


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, pred, label, *args):
        """Optional pre-processing hook run on device outputs."""
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = np.asarray(pred)
        label = np.asarray(label)
        pred_idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:  # one-hot or [N,1] index
            if label.shape[-1] == pred.shape[-1]:
                label = np.argmax(label, -1)
            else:
                label = label.squeeze(-1)
        correct = (pred_idx == label[..., None])
        return correct

    def update(self, correct, *args):
        correct = np.asarray(correct)
        n = correct.reshape(-1, correct.shape[-1]).shape[0]
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(-1).sum()
            self.total[i] += c
            self.count[i] += n
        accs = self.total / np.maximum(self.count, 1)
        return accs[0] if len(self.topk) == 1 else accs

    def accumulate(self):
        accs = self.total / np.maximum(self.count, 1)
        return float(accs[0]) if len(self.topk) == 1 else accs.tolist()

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return [self._name]


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return [self._name]


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]
        labels = np.asarray(labels).reshape(-1)
        bins = np.minimum((preds * self.num_thresholds).astype(np.int64),
                          self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            p = self._stat_pos[i]
            n = self._stat_neg[i]
            auc += n * (tot_pos + p / 2.0)
            tot_pos += p
            tot_neg += n
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return float(auc / (tot_pos * tot_neg))

    def name(self):
        return [self._name]


def accuracy(input, label, k: int = 1, correct=None, total=None, name=None):
    """Functional parity: paddle.metric.accuracy — top-k accuracy of
    ``input`` [N, C] probabilities/logits vs ``label`` [N] or [N, 1].

    The reference's ``correct``/``total`` out-tensors have no functional
    analog here; passing them raises instead of silently ignoring."""
    if correct is not None or total is not None:
        raise ValueError(
            "metric.accuracy: correct/total out-tensors are not supported "
            "in the functional TPU port — read the returned accuracy")
    import jax.numpy as jnp
    input = jnp.asarray(input)
    label = jnp.asarray(label).reshape(-1)
    topk = jnp.argsort(-input, axis=-1)[:, :k]
    hit = jnp.any(topk == label[:, None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))
