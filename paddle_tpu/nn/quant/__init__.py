"""paddle.nn.quant parity — LLM weight-only quantization.

Reference: python/paddle/nn/quant/quantized_linear.py —
``weight_quantize``, ``weight_dequantize``, ``weight_only_linear``,
``llm_int8_linear`` (backed by paddle/phi/kernels/fusion/gpu
weight_only_linear kernels and cutlass int8 GEMMs).

TPU-native design: weight-only int8/int4 keeps activations in
bf16/f32 and stores weights quantized per output channel; the forward
contracts against the raw integer weights and applies the per-channel
scale AFTER the dot (exact for per-output-channel scales), so HBM
traffic drops by 2-4x (the decode-time bottleneck) and no full-size
dequantized weight is ever materialized, while the MXU still runs the
contraction in bf16.  ``llm_int8_linear``
implements the LLM.int8 outlier decomposition (arXiv 2208.07339): the
few activation columns above ``threshold`` run in float, the rest in
int8 x int8 -> int32 on the MXU's double-rate integer path.

Deviations from the reference, documented: weights are stored in the
natural ``[in, out]`` layout with scale ``[out]`` (the reference packs
arch-specific CUTLASS tile layouts — meaningless on TPU); int4 packs
two nibbles per int8 byte along the input axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "WeightOnlyLinear", "LLMInt8Linear",
           "convert_to_weight_only"]


def weight_quantize(x, algo: str = "weight_only_int8", group_size: int = -1):
    """Quantize a ``[in, out]`` weight per output channel.

    Returns ``(quantized, scale)``: int8 ``[in, out]`` (int4: packed
    ``[in//2, out]``) and f32 scale ``[out]``.
    """
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unsupported algo: {algo}")
    if group_size != -1:
        raise NotImplementedError(
            "groupwise quantization not implemented; use per-channel "
            "(group_size=-1)")
    from ...quantization.quanters import absmax_quantize
    if algo == "weight_only_int4":
        q, scale = absmax_quantize(x, channel_axis=1, bit_length=4)
        if q.shape[0] % 2:
            raise ValueError("int4 packing needs an even input dim")
        lo = q[0::2] & 0xF
        hi = (q[1::2] & 0xF) << 4
        return (lo | hi).astype(jnp.int8), scale
    return absmax_quantize(x, channel_axis=1, bit_length=8)


def _unpack_int4(q):
    """[in//2, out] packed -> [in, out] int8 in [-8, 7]."""
    lo = (q & 0xF).astype(jnp.int8)
    hi = ((q >> 4) & 0xF).astype(jnp.int8)
    # sign-extend 4-bit two's complement
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=1)           # [in//2, 2, out]
    return out.reshape(-1, q.shape[-1])         # [in, out]


def weight_dequantize(x, scale, algo: str = "weight_only_int8",
                      out_dtype=jnp.float32):
    """Inverse of :func:`weight_quantize`."""
    if algo == "weight_only_int4":
        w = _unpack_int4(x).astype(jnp.float32) / 7.0
    else:
        w = x.astype(jnp.float32) / 127.0
    return (w * scale).astype(out_dtype)  # graftlint: disable=memory-budget -- the documented inverse: materializing the float weight IS this function's contract, and no decode path calls it


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype: str = "int8", arch=None,
                       group_size: int = -1):
    """y = (x @ w_int) * scale + bias — weights stay quantized in HBM
    and the rescale runs AFTER the contraction.

    Scale-after-dot is exact for per-output-channel scales
    (``sum_i x_i * (q_ij * s_j) == (sum_i x_i * q_ij) * s_j``) and is
    what makes int8 decode actually beat fp: dequantize-then-matmul
    rebuilds the full [in, out] float weight every step — an O(in*out)
    multiply XLA does NOT reliably sink into the dot, which made the
    bench's gpt_decode_int8 row SLOWER than fp (0.87x in BENCH_r05).
    After the dot the rescale is O(out) per row."""
    if weight_dtype == "int4":
        w_int = _unpack_int4(weight).astype(x.dtype)
        denom = 7.0
    else:
        w_int = weight.astype(x.dtype)
        denom = 127.0
    y = (x @ w_int).astype(jnp.float32) * (weight_scale / denom)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold: float = 6.0):
    """LLM.int8: split activation columns by magnitude; outlier columns
    multiply the dequantized float weights, the rest take the
    int8 x int8 -> int32 MXU path.

    ``weight`` int8 ``[in, out]``, ``weight_scale`` ``[out]``.
    """
    xf = x.astype(jnp.float32)
    # per-input-feature outlier mask over all leading dims (static shape:
    # the mask is data-dependent but dense — no gather/scatter)
    colmax = jnp.max(jnp.abs(xf), axis=tuple(range(x.ndim - 1)))
    outlier = colmax >= threshold                             # [in]
    x_out = jnp.where(outlier, xf, 0.0)
    x_int_part = jnp.where(outlier, 0.0, xf)
    # int8 path: per-tensor absmax of the non-outlier part
    s_a = jnp.maximum(jnp.max(jnp.abs(x_int_part)), 1e-8)
    xq = jnp.clip(jnp.round(x_int_part / s_a * 127), -127,
                  127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, weight,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (s_a * weight_scale / (127.0 * 127.0))
    # float path for outliers
    w_f = weight.astype(jnp.float32) / 127.0 * weight_scale  # graftlint: disable=memory-budget -- LLM.int8's outlier float path materializes the weight once by design; not on any serving hot path
    y = y + x_out @ w_f
    if bias is not None:
        y = y + bias
    return y.astype(x.dtype)


from ..layer import Layer as _Layer


class WeightOnlyLinear(_Layer):
    """Drop-in inference replacement for a dense linear: the weight lives
    in HBM quantized (int8, or int4 nibble-packed); forward is
    :func:`weight_only_linear`, so the dequant fuses into the matmul."""

    def __init__(self, weight, bias, weight_dtype: str = "int8"):
        super().__init__()
        if weight_dtype not in ("int8", "int4"):
            raise ValueError(
                f"weight_dtype must be int8 or int4, got {weight_dtype!r}")
        algo = ("weight_only_int4" if weight_dtype == "int4"
                else "weight_only_int8")
        q, scale = weight_quantize(weight, algo=algo)
        self.in_features = int(weight.shape[0])
        self.out_features = int(weight.shape[1])
        self.weight_dtype = weight_dtype
        self.register_buffer("w_quant", q)
        self.register_buffer("w_scale", scale)
        self.register_buffer("bias", bias)

    def forward(self, x):
        return weight_only_linear(x, self.w_quant, self.bias, self.w_scale,
                                  weight_dtype=self.weight_dtype)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, "
                f"weight_dtype={self.weight_dtype}")


class LLMInt8Linear(_Layer):
    """Inference linear running the LLM.int8 outlier decomposition
    (arXiv 2208.07339): activations split by column magnitude — outlier
    columns multiply dequantized float weights, the rest ride the
    int8 x int8 -> int32 MXU path (:func:`llm_int8_linear`)."""

    def __init__(self, weight, bias, threshold: float = 6.0):
        super().__init__()
        q, scale = weight_quantize(weight, algo="weight_only_int8")
        self.in_features = int(weight.shape[0])
        self.out_features = int(weight.shape[1])
        self.threshold = float(threshold)
        self.register_buffer("w_quant", q)
        self.register_buffer("w_scale", scale)
        self.register_buffer("bias", bias)

    def forward(self, x):
        return llm_int8_linear(x, self.w_quant, self.bias, self.w_scale,
                               threshold=self.threshold)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, "
                f"threshold={self.threshold}")


def convert_to_weight_only(model, weight_dtype: str = "int8",
                           inplace: bool = False, threshold: float = 6.0):
    """Swap every dense linear in ``model`` — ``nn.Linear`` AND the
    Megatron ``ColumnParallelLinear``/``RowParallelLinear`` (their
    single-device forward is the same ``x @ W + b``) — for a
    :class:`WeightOnlyLinear` holding its quantized weight: the
    LLM-deployment path, convert once and ``model.generate`` (or any
    forward) runs with 2-4x less weight HBM traffic.

    SINGLE-DEVICE inference transform (like the reference's weight-only
    pipeline, which rewrites the inference program): the parallel
    layers' mp sharding constraints/collectives are dropped by the swap,
    so convert the dense model you deploy, not a live mp>1 trainer.
    Embeddings, norms, and tied output heads are untouched.  int4
    requires every converted linear's input dim to be even.
    ``weight_dtype="llm.int8"`` swaps in :class:`LLMInt8Linear`
    (outlier-decomposed int8 matmuls, ``threshold`` controlling the
    outlier column cut).
    """
    if weight_dtype not in ("int8", "int4", "llm.int8"):
        raise ValueError(
            f"weight_dtype must be int8/int4/llm.int8, got "
            f"{weight_dtype!r}")
    import copy

    from ..layer import Layer
    from ..layers.common import Linear
    from ...distributed.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    if not isinstance(model, Layer):
        raise TypeError("convert_to_weight_only expects an nn.Layer")
    # isinstance (not exact type): sequence-parallel variants subclass the
    # mp layers and share the same dense single-device forward
    kinds = (Linear, ColumnParallelLinear, RowParallelLinear)

    def quantize(layer, cache):
        if id(layer) not in cache:
            if weight_dtype == "llm.int8":
                cache[id(layer)] = LLMInt8Linear(layer.weight, layer.bias,
                                                 threshold=threshold)
            else:
                cache[id(layer)] = WeightOnlyLinear(
                    layer.weight, layer.bias, weight_dtype=weight_dtype)
        return cache[id(layer)]

    if isinstance(model, kinds):
        # bare linear: convert it directly instead of a silent no-op
        return quantize(model, {})
    if not inplace:
        model = copy.deepcopy(model)
    # walk parent slots directly (NOT named_sublayers, which dedups by
    # id): a linear shared between two parents must be swapped at EVERY
    # slot, and the id-keyed cache keeps the quantized copy shared too
    cache = {}
    seen = set()

    def walk(parent):
        if id(parent) in seen:
            return
        seen.add(id(parent))
        for key, child in list(parent._sub_layers.items()):
            if child is None:
                continue
            if isinstance(child, (WeightOnlyLinear, LLMInt8Linear)):
                continue
            if isinstance(child, kinds):
                parent._sub_layers[key] = quantize(child, cache)
            else:
                walk(child)

    walk(model)
    return model
