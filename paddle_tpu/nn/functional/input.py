"""Embedding / one-hot functionals.

Reference: python/paddle/nn/functional/input.py — one_hot, embedding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["one_hot", "embedding"]


def one_hot(x, num_classes: int, name=None):
    return jax.nn.one_hot(x.astype(jnp.int32), num_classes, dtype=jnp.float32)


def embedding(x, weight, padding_idx=None, sparse: bool = False, name=None):
    """Gather rows; padding_idx rows produce zeros with zero grad (parity:
    paddle embedding padding_idx semantics)."""
    idx = x.astype(jnp.int32)
    out = jnp.take(weight, idx, axis=0)
    if padding_idx is not None:
        if padding_idx < 0:
            padding_idx = weight.shape[0] + padding_idx
        mask = (idx != padding_idx)[..., None]
        out = jnp.where(mask, out, 0.0)
    return out
