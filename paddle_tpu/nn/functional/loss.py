"""Loss functionals.

Reference: python/paddle/nn/functional/loss.py — cross_entropy,
softmax_with_cross_entropy, mse_loss, l1_loss, nll_loss, bce losses,
smooth_l1, kl_div, margin losses; the vocab-parallel variant
(c_softmax_with_cross_entropy) lives in distributed/ (SURVEY.md §2.3 TP).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["cross_entropy", "softmax_with_cross_entropy",
           "chunked_softmax_cross_entropy", "mse_loss",
           "l1_loss", "nll_loss", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "smooth_l1_loss", "kl_div",
           "margin_ranking_loss", "hinge_embedding_loss", "cosine_embedding_loss",
           "ctc_loss", "sigmoid_focal_loss", "square_error_cost",
           "log_loss", "triplet_margin_loss",
           "dice_loss", "soft_margin_loss", "multi_label_soft_margin_loss",
           "gaussian_nll_loss", "poisson_nll_loss"]


def _reduce(loss, reduction: str):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index: int = -100,
                  reduction: str = "mean", soft_label: bool = False,
                  axis: int = -1, use_softmax: bool = True,
                  label_smoothing: float = 0.0, name=None):
    """Parity: paddle F.cross_entropy (hard/soft labels, ignore_index,
    class weights, label smoothing).  Computed in fp32 for stability."""
    x = input.astype(jnp.float32)
    logp = jax.nn.log_softmax(x, axis=axis) if use_softmax else jnp.log(
        jnp.clip(x, 1e-12))
    nclass = x.shape[axis]
    if soft_label:
        tgt = label.astype(jnp.float32)
        if label_smoothing > 0:
            tgt = (1 - label_smoothing) * tgt + label_smoothing / nclass
        loss = -jnp.sum(tgt * logp, axis=axis)
        valid = None
    else:
        lbl = label
        if lbl.ndim == x.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        valid = (lbl != ignore_index)
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis)
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            mean_logp = jnp.mean(logp, axis=axis)
            loss = -(1 - label_smoothing) * picked - label_smoothing * mean_logp
        else:
            loss = -picked
        w = jnp.take(weight, safe) if weight is not None else None
        if w is not None:
            loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        if valid is not None:
            denom = jnp.sum(jnp.where(valid, w, 0.0)) if w is not None \
                else jnp.sum(valid.astype(jnp.float32))
            return jnp.sum(loss) / jnp.maximum(denom, 1.0)
        return jnp.mean(loss)
    return _reduce(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label: bool = False,
                               ignore_index: int = -100, numeric_stable_mode=True,
                               return_softmax: bool = False, axis: int = -1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = jnp.expand_dims(loss, axis)
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction: str = "mean", name=None):
    return _reduce(jnp.square(input - label), reduction)


def square_error_cost(input, label):
    return jnp.square(input - label)


def l1_loss(input, label, reduction: str = "mean", name=None):
    return _reduce(jnp.abs(input - label), reduction)


def nll_loss(input, label, weight=None, ignore_index: int = -100,
             reduction: str = "mean", name=None):
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(input, jnp.expand_dims(safe, 1), axis=1)
    picked = jnp.squeeze(picked, 1)
    loss = -picked
    if weight is not None:
        w = jnp.take(weight, safe)
        loss = loss * w
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.sum(jnp.take(weight, safe) * valid) if weight is not None \
            else jnp.sum(valid)
        return jnp.sum(loss) / jnp.maximum(denom, 1.0)
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction: str = "mean",
                         name=None):
    x = jnp.clip(input, 1e-12, 1.0 - 1e-7)
    loss = -(label * jnp.log(x) + (1 - label) * jnp.log1p(-x))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction: str = "mean",
                                     pos_weight=None, name=None):
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * jnp.logaddexp(0.0, -logit)
    else:
        loss = jax.nn.relu(logit) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction: str = "mean", delta: float = 1.0,
                   name=None):
    d = input - label
    abs_d = jnp.abs(d)
    loss = jnp.where(abs_d < delta, 0.5 * d * d / delta, abs_d - 0.5 * delta)
    return _reduce(loss, reduction)


def kl_div(input, label, reduction: str = "mean", log_target: bool = False,
           name=None):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        safe = jnp.clip(label, 1e-12)
        loss = label * (jnp.log(safe) - input)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin: float = 0.0,
                        reduction: str = "mean", name=None):
    loss = jax.nn.relu(-label * (input - other) + margin)
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin: float = 1.0,
                         reduction: str = "mean", name=None):
    loss = jnp.where(label == 1, input, jax.nn.relu(margin - input))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin: float = 0.0,
                          reduction: str = "mean", name=None):
    cos = jnp.sum(input1 * input2, -1) / (
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1) + 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jax.nn.relu(cos - margin))
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha: float = 0.25,
                       gamma: float = 2.0, reduction: str = "sum", name=None):
    p = jax.nn.sigmoid(logit)
    ce = binary_cross_entropy_with_logits(logit, label, reduction="none")
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


def log_loss(input, label, epsilon: float = 1e-4, name=None):
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(
        1 - input + epsilon)


def triplet_margin_loss(input, positive, negative, margin: float = 1.0,
                        p: float = 2.0, epsilon: float = 1e-6, swap: bool = False,
                        reduction: str = "mean", name=None):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), -1), 1 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce(jax.nn.relu(d_pos - d_neg + margin), reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank: int = 0,
             reduction: str = "mean", norm_by_times: bool = False):
    """CTC over optax.ctc_loss.

    Layout follows the reference exactly (paddle F.ctc_loss): log_probs is
    time-major [T_max, B, K]; labels [B, L_max]; optax wants batch-major, so
    one deterministic transpose — no shape guessing.
    """
    import optax
    logits = jnp.transpose(log_probs, (1, 0, 2))  # [B, T, K]
    b, t, k = logits.shape
    logit_pad = (jnp.arange(t)[None, :] >= input_lengths[:, None]).astype(jnp.float32)
    label_pad = (jnp.arange(labels.shape[1])[None, :] >= label_lengths[:, None]
                 ).astype(jnp.float32)
    loss = optax.ctc_loss(logits, logit_pad, labels, label_pad, blank_id=blank)
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon: float = 1e-5, name=None):
    """Reference: F.dice_loss — input [N, ..., C] probabilities, label
    [N, ..., 1] int class ids; 1 - dice coefficient per batch row."""
    input = jnp.asarray(input)
    label = jnp.asarray(label)
    C = input.shape[-1]
    one_hot = jax.nn.one_hot(label[..., 0], C, dtype=input.dtype)
    red = tuple(range(1, input.ndim))
    inter = jnp.sum(input * one_hot, axis=red)
    union = jnp.sum(input, axis=red) + jnp.sum(one_hot, axis=red)
    dice = (2.0 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1.0 - dice)


def soft_margin_loss(input, label, reduction: str = "mean", name=None):
    """Reference: log(1 + exp(-label * input)), label in {-1, 1}."""
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    out = jnp.log1p(jnp.exp(-label * input))
    return _reduce(out, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction: str = "mean", name=None):
    """Reference: mean over classes of BCE-with-logits vs multi-hot label."""
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    logsig = jax.nn.log_sigmoid
    per = -(label * logsig(input) + (1 - label) * logsig(-input))
    if weight is not None:
        per = per * jnp.asarray(weight, input.dtype)
    out = jnp.mean(per, axis=-1)
    return _reduce(out, reduction)


def gaussian_nll_loss(input, label, variance, full: bool = False,
                      epsilon: float = 1e-6, reduction: str = "mean",
                      name=None):
    """Reference: 0.5*(log(var) + (x-mu)^2/var) (+ const when full)."""
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    var = jnp.maximum(jnp.asarray(variance, input.dtype), epsilon)
    out = 0.5 * (jnp.log(var) + (label - input) ** 2 / var)
    if full:
        import math as _m
        out = out + 0.5 * _m.log(2 * _m.pi)
    return _reduce(out, reduction)


def poisson_nll_loss(input, label, log_input: bool = True,
                     full: bool = False, epsilon: float = 1e-8,
                     reduction: str = "mean", name=None):
    """Reference: exp(x) - y*x (log_input) or x - y*log(x+eps)."""
    input = jnp.asarray(input)
    label = jnp.asarray(label, input.dtype)
    if log_input:
        out = jnp.exp(input) - label * input
    else:
        out = input - label * jnp.log(input + epsilon)
    if full:
        # Stirling approximation for label! (label > 1)
        stirling = (label * jnp.log(label) - label
                    + 0.5 * jnp.log(2 * jnp.pi * label))
        out = out + jnp.where(label > 1, stirling, 0.0)
    return _reduce(out, reduction)


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

def multi_margin_loss(input, label, p: int = 1, margin: float = 1.0,
                      weight=None, reduction: str = "mean", name=None):
    """Multi-class margin loss (reference: multi_margin_loss; torch
    semantics: mean over classes of max(0, margin - x_y + x_i)^p)."""
    x = jnp.asarray(input)
    lbl = jnp.asarray(label).astype(jnp.int32)
    n, c = x.shape
    x_y = jnp.take_along_axis(x, lbl[:, None], axis=1)       # [N, 1]
    m = jnp.maximum(0.0, margin - x_y + x)                   # [N, C]
    if p != 1:
        m = m ** p
    if weight is not None:
        m = m * jnp.asarray(weight)[lbl][:, None]
    # the true-class term contributes margin^p; zero it like the reference
    m = m * (1.0 - jax.nn.one_hot(lbl, c, dtype=x.dtype))
    out = jnp.sum(m, axis=1) / c
    return _reduce(out, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None,
                                      margin: float = 1.0,
                                      swap: bool = False,
                                      reduction: str = "mean", name=None):
    """Triplet loss with a custom distance callable (reference:
    triplet_margin_with_distance_loss)."""
    if distance_function is None:
        def distance_function(a, b):
            return jnp.sqrt(jnp.maximum(
                jnp.sum((a - b) ** 2, axis=-1), 1e-12))
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, distance_function(positive, negative))
    out = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce(out, reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: F.hsigmoid_loss /
    hierarchical_sigmoid_op).  Default tree: complete binary tree over
    ``num_classes`` leaves, depth D = ceil(log2(C)); internal node ids are
    the heap path of (label + C) >> k, matching the reference's default
    coding (code = bit, node index = heap parent - 1).

    weight: [num_classes - 1, D_in]; bias: [num_classes - 1].
    Custom trees ride path_table [N, L] (node ids, -1 padded) and
    path_code [N, L] (0/1 codes).
    """
    x = jnp.asarray(input)
    lbl = jnp.asarray(label).astype(jnp.int32).reshape(-1)
    if path_table is None:
        c = int(num_classes)
        depth = max(int(np.ceil(np.log2(c))), 1)
        heap = lbl + c                                  # leaf heap id
        ks = jnp.arange(depth, 0, -1)                   # D..1
        anc = (heap[:, None] >> ks[None, :])            # ancestors, root..  
        codes = (heap[:, None] >> (ks[None, :] - 1)) & 1
        nodes = anc - 1                                 # node index
        valid = anc >= 1
    else:
        nodes = jnp.asarray(path_table).astype(jnp.int32)
        codes = jnp.asarray(path_code).astype(jnp.int32)
        valid = nodes >= 0
        nodes = jnp.maximum(nodes, 0)
    w = jnp.asarray(weight)[nodes]                      # [N, L, D_in]
    logits = jnp.einsum("nld,nd->nl", w, x)
    if bias is not None:
        logits = logits + jnp.asarray(bias).reshape(-1)[nodes]
    # code 1 -> sigmoid(logit), 0 -> 1 - sigmoid(logit); NLL over the path
    ll = jax.nn.log_sigmoid(logits) * codes + \
        jax.nn.log_sigmoid(-logits) * (1 - codes)
    # reference returns the per-sample cost [N, 1], NO reduction
    return -jnp.sum(jnp.where(valid, ll, 0.0), axis=1, keepdims=True)


def margin_cross_entropy(logits, label, margin1: float = 1.0,
                         margin2: float = 0.5, margin3: float = 0.0,
                         scale: float = 64.0, group=None,
                         return_softmax: bool = False,
                         reduction: str = "mean", name=None):
    """ArcFace/CosFace-style margin softmax CE (reference:
    margin_cross_entropy_op; PLSC's headline loss).  cos(theta) logits get
    cos(m1*theta + m2) - m3 on the true class, then scaled CE.  ``group``
    names a mesh axis for class-parallel logits (vocab-sharded semantics —
    GSPMD reduces over it)."""
    x = jnp.asarray(logits).astype(jnp.float32)
    lbl = jnp.asarray(label).astype(jnp.int32)
    cos_t = jnp.clip(jnp.take_along_axis(x, lbl[:, None], axis=1),
                     -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    target = jnp.cos(margin1 * theta + margin2) - margin3
    onehot = jax.nn.one_hot(lbl, x.shape[-1], dtype=x.dtype)
    adjusted = x * (1 - onehot) + target * onehot
    z = adjusted * scale
    m = jnp.max(z, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(z - m), axis=-1, keepdims=True))
    picked = jnp.take_along_axis(z, lbl[:, None], axis=1)
    loss = (lse - picked)[:, 0]
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jax.nn.softmax(z, axis=-1)
    return loss


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive (clustered) softmax (reference:
    F.adaptive_log_softmax_with_loss; Grave et al. 2017).

    ``cutoffs`` INCLUDES the total class count as its last element
    ([shortlist, c1, ..., n_classes]); head_weight [D, H] with
    H = cutoffs[0] + n_clusters; each tail_weights[i] is the pair
    ([D, d_i], [d_i, size_i]) low-rank projection, matching the reference
    layer's parameter layout.  Returns (per-sample log-prob of the true
    class, mean NLL loss).
    """
    x = jnp.asarray(input)
    lbl = jnp.asarray(label).astype(jnp.int32)
    cutoffs = list(cutoffs)
    n_clusters = len(cutoffs) - 1
    shortlist = cutoffs[0]
    head_logits = x @ jnp.asarray(head_weight)
    if head_bias is not None:
        head_logits = head_logits + jnp.asarray(head_bias)
    head_logp = jax.nn.log_softmax(head_logits, axis=-1)

    in_short = lbl < shortlist
    safe_short = jnp.where(in_short, lbl, 0)
    out = jnp.take_along_axis(head_logp, safe_short[:, None], axis=1)[:, 0]
    out = jnp.where(in_short, out, 0.0)

    # cluster i covers label span [cutoffs[i], cutoffs[i+1])
    spans = [(cutoffs[i], cutoffs[i + 1]) for i in range(n_clusters)]
    for i, (lo_i, hi_i) in enumerate(spans):
        proj, emb = tail_weights[i]
        tail_logp = jax.nn.log_softmax(
            (x @ jnp.asarray(proj)) @ jnp.asarray(emb), axis=-1)
        in_c = (lbl >= lo_i) & (lbl < hi_i)
        safe = jnp.where(in_c, lbl - lo_i, 0)
        cluster_lp = head_logp[:, shortlist + i]
        lp = cluster_lp + jnp.take_along_axis(
            tail_logp, safe[:, None], axis=1)[:, 0]
        out = jnp.where(in_c, lp, out)
    loss = -jnp.mean(out)
    return out, loss


__all__ += ["multi_margin_loss", "triplet_margin_with_distance_loss",
            "hsigmoid_loss", "margin_cross_entropy",
            "adaptive_log_softmax_with_loss"]


def rnnt_loss(input, label, input_lengths, label_lengths, blank: int = 0,
              fastemit_lambda: float = 0.001, reduction: str = "mean",
              name=None):
    """RNN-Transducer loss (reference: paddle.nn.functional.rnnt_loss over
    the warprnnt kernel).

    ``input`` [B, T, U+1, V] joint-network LOGITS (log-softmax applied
    internally, like warprnnt); ``label`` [B, U] ints; ``input_lengths``
    [B], ``label_lengths`` [B].  Forward DP over the (T, U) lattice:

        alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
                                alpha[t, u-1] + emit(t, u-1))
        -logP = -(alpha[T-1, U] + blank(T-1, U))

    run as a lax.scan over t with an inner scan over u — static shapes,
    ragged lengths handled by masking.  FastEmit regularization follows
    the warp-transducer gradient contract exactly: the reported loss is
    ``L`` while the emission-path gradient is scaled by ``1 + lambda``,
    via a value-neutral ``lambda * (L_emit - stop_gradient(L_emit))``
    term where ``L_emit`` recomputes the DP with the blank scores
    stop-gradiented.
    """
    x = jnp.asarray(input)
    if x.ndim != 4:
        raise ValueError(f"rnnt_loss expects input [B, T, U+1, V], got "
                         f"shape {tuple(x.shape)}")
    b, t_max, u1, v = x.shape
    if not 0 <= blank < v:
        raise ValueError(f"blank={blank} outside [0, V={v}) — JAX index "
                         f"clamping would silently retarget it")
    labels = jnp.asarray(label, jnp.int32)
    if labels.shape[1] + 1 != u1:
        raise ValueError(
            f"label dim {labels.shape[1]} must be input.shape[2]-1="
            f"{u1 - 1}")
    t_len = jnp.asarray(input_lengths, jnp.int32)
    u_len = jnp.asarray(label_lengths, jnp.int32)
    try:                       # eager: reject lengths past the tensor dims
        if int(jnp.max(t_len)) > t_max or int(jnp.max(u_len)) > u1 - 1:
            raise ValueError(
                f"input_lengths/label_lengths exceed input dims "
                f"(T={t_max}, U={u1 - 1}) — the kernel would silently "
                f"truncate")
    except jax.errors.ConcretizationTypeError:
        pass                   # traced: lengths are dynamic, caller's duty
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)

    def neg_log_like(lp):
        # lp [B, T, U+1, V]
        blank_lp = lp[..., blank]                          # [B, T, U+1]
        emit_lp = jnp.take_along_axis(
            lp[:, :, :-1, :], labels[:, None, :, None], axis=-1
        )[..., 0]                                          # [B, T, U]
        neg_inf = jnp.float32(-1e30)

        def row(alpha_prev, inputs):
            # alpha_prev [B, U+1] = alpha[t-1, :]; returns alpha[t, :]
            t, (blank_t, emit_t) = inputs
            from_below = jnp.where(
                t == 0,
                jnp.where(jnp.arange(u1)[None] == 0, 0.0, neg_inf),
                alpha_prev + blank_t)                      # via blank(t-1, u)

            def cell(carry, uin):
                u, below = uin                             # below [B]
                left = carry + emit_t_prev_col(u)
                val = jnp.where(u == 0, below,
                                jnp.logaddexp(below, left))
                # t == 0 row: only u == 0 is reachable via the init above;
                # left transitions use emit(t=0, u-1) which IS valid
                return val, val

            def emit_t_prev_col(u):
                # emit(t, u-1) for the left transition into (t, u); the
                # u == 0 read of the pad column is discarded by the where
                return emit_row[jnp.arange(b), jnp.maximum(u - 1, 0)]

            # pad one column so U == 0 (empty labels) still indexes
            emit_row = jnp.concatenate(
                [emit_t, jnp.full((b, 1), neg_inf)], axis=1)
            _, cols = jax.lax.scan(
                cell, jnp.full((b,), neg_inf),
                (jnp.arange(u1), jnp.moveaxis(from_below, 1, 0)))
            alpha_t = jnp.moveaxis(cols, 0, 1)             # [B, U+1]
            return alpha_t, alpha_t

        ts = jnp.arange(t_max)
        blanks = jnp.moveaxis(blank_lp, 1, 0)              # [T, B, U+1]
        emits = jnp.moveaxis(emit_lp, 1, 0)                # [T, B, U]
        # row t consumes blank(t-1, u): shift the blank rows by one
        blanks_prev = jnp.concatenate(
            [jnp.zeros((1, b, u1), jnp.float32), blanks[:-1]], axis=0)
        _, alphas = jax.lax.scan(row, jnp.full((b, u1), neg_inf),
                                 (ts, (blanks_prev, emits)))
        # alphas [T, B, U+1]; terminal: alpha[T_b-1, U_b] + blank(T_b-1, U_b)
        bt = jnp.clip(t_len - 1, 0, t_max - 1)
        alpha_final = alphas[bt, jnp.arange(b), u_len]
        blank_final = blank_lp[jnp.arange(b), bt, u_len]
        return -(alpha_final + blank_final)

    nll = neg_log_like(logp)
    if fastemit_lambda:
        # gradient-level FastEmit: lambda extra copies of the emission-path
        # gradient (values identical, blank path stop-gradiented)
        lp_fe = jnp.concatenate(
            [logp[..., :blank],
             jax.lax.stop_gradient(logp[..., blank:blank + 1]),
             logp[..., blank + 1:]], axis=-1)
        # value-neutral: the extra term is zero in value (so the reported
        # loss is exactly L, the warprnnt contract) but contributes the
        # lambda-scaled emission-path gradient
        fe = neg_log_like(lp_fe)
        nll = nll + fastemit_lambda * (fe - jax.lax.stop_gradient(fe))
    return _reduce(nll, reduction)


__all__ += ["rnnt_loss"]


def chunked_softmax_cross_entropy(hidden, weight, labels, n_chunks=8,
                                  ignore_index: int = -100):
    """Causal-LM head + softmax CE WITHOUT materializing the [N, V]
    logits: the vocabulary is processed in chunks with an online
    (running max / sum-exp) softmax, and the backward recomputes each
    chunk's logits — peak activation drops from O(N*V) to O(N*V/k).

    ``hidden`` [N, h], ``weight`` [V, h] (the tied embedding table),
    ``labels`` [N] int -> per-token loss [N] (f32).

    Reference context: c_softmax_with_cross_entropy fuses the same
    pattern across mp shards; this is the SINGLE-DEVICE analog where
    the full-vocab logits tensor itself is the memory hog (e.g. the
    flagship bench: [4, 2048, 50304] f32 logits + grad ~ 3.3 GB of a
    16 GB chip — the difference between b4 and b6 fitting HBM).
    ``ignore_index`` labels (padding) contribute zero loss AND zero
    gradient, matching parallel_cross_entropy's masking.
    Falls back to the dense path when V % n_chunks != 0.

    All internal math is f32 (matching parallel_cross_entropy); the
    returned cotangents match the primals' dtypes.
    """
    N, h = hidden.shape
    V = weight.shape[0]
    valid = labels.astype(jnp.int32) != ignore_index
    # clamp to [0, V-1] so out-of-range labels (not ignore_index) pick the
    # same (clamped) logit on BOTH the chunked and dense paths — before
    # this, the chunked path silently returned loss=lse (picked=0) while
    # the dense path clamped via take_along_axis: two different wrong
    # answers for one invalid input (ADVICE r5)
    lbl = jnp.clip(jnp.where(valid, labels.astype(jnp.int32), 0), 0, V - 1)
    if n_chunks <= 1 or V % n_chunks:
        if n_chunks > 1:
            import warnings
            warnings.warn(
                f"chunked_softmax_cross_entropy: vocab {V} not divisible "
                f"by n_chunks={n_chunks} — falling back to the DENSE "
                f"path (full [N, V] logits materialized); pick a chunk "
                f"count dividing the vocab to get the memory saving",
                RuntimeWarning, stacklevel=2)
        logits = (hidden.astype(jnp.float32)
                  @ weight.astype(jnp.float32).T)
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), -1))
        picked = jnp.take_along_axis(logits, lbl[:, None], 1)[:, 0]
        return jnp.where(valid, lse - picked, 0.0)

    C = V // n_chunks

    def _fwd_scan(hid32, w_chunks):
        def body(carry, xs):
            m, s, picked = carry
            wc, i = xs
            lg = hid32 @ wc.astype(jnp.float32).T          # [N, C]
            cm = jnp.maximum(m, jnp.max(lg, -1))
            s = s * jnp.exp(m - cm) + jnp.sum(
                jnp.exp(lg - cm[:, None]), -1)
            local = lbl - i * C
            ok = (local >= 0) & (local < C)
            pick = jnp.take_along_axis(
                lg, jnp.clip(local, 0, C - 1)[:, None], 1)[:, 0]
            picked = jnp.where(ok, pick, picked)
            return (cm, s, picked), None

        init = (jnp.full((N,), -jnp.inf, jnp.float32),
                jnp.zeros((N,), jnp.float32),
                jnp.zeros((N,), jnp.float32))
        (m, s, picked), _ = jax.lax.scan(
            body, init, (w_chunks, jnp.arange(n_chunks)))
        lse = m + jnp.log(s)
        return jnp.where(valid, lse - picked, 0.0), lse

    @jax.custom_vjp
    def ce(hid, w):
        w_chunks = w.reshape(n_chunks, C, h)
        return _fwd_scan(hid.astype(jnp.float32), w_chunks)[0]

    def fwd(hid, w):
        w_chunks = w.reshape(n_chunks, C, h)
        loss, lse = _fwd_scan(hid.astype(jnp.float32), w_chunks)
        return loss, (hid, w, lse)

    def bwd(res, g):
        hid, w, lse = res
        hid32 = hid.astype(jnp.float32)
        w_chunks = w.reshape(n_chunks, C, h)
        gc = (g.astype(jnp.float32) * valid)[:, None]      # [N, 1]

        def body(gh, xs):
            wc, i = xs
            wc32 = wc.astype(jnp.float32)
            lg = hid32 @ wc32.T                            # [N, C]
            p = jnp.exp(lg - lse[:, None])
            local = lbl - i * C
            ok = (local >= 0) & (local < C)
            onehot = jax.nn.one_hot(
                jnp.where(ok, local, C), C,
                dtype=jnp.float32)                         # ok row else 0
            delta = (p - onehot) * gc                      # [N, C]
            gh = gh + delta @ wc32
            gw_c = delta.T @ hid32                         # [C, h]
            return gh, gw_c

        gh, gw = jax.lax.scan(
            body, jnp.zeros((N, h), jnp.float32),
            (w_chunks, jnp.arange(n_chunks)))
        return (gh.astype(hidden.dtype),
                gw.reshape(V, h).astype(weight.dtype))

    ce.defvjp(fwd, bwd)
    return ce(hidden, weight)
