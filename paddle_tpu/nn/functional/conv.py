"""Convolution functionals over lax.conv_general_dilated.

Reference: python/paddle/nn/functional/conv.py — conv1d/2d/3d(+transpose)
backed by phi/kernels/gpudnn/conv_kernel.cu (cuDNN).  On TPU the conv maps
straight onto the MXU via XLA; weight layout follows paddle: [out_c,
in_c/groups, *spatial].
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


def _padding(padding, n):
    """paddle padding: int, list of ints, list of pairs, or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == n:
            return [(p, p) for p in padding]
        if len(padding) == 2 * n:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
        if len(padding) == 1:
            return [(padding[0], padding[0])] * n
    return [tuple(p) for p in padding]


def _dimnums(n, channel_last):
    if n == 1:
        return ("NWC", "OIW"[:3], "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "OIHW", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "OIDHW", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    from ...amp.auto_cast import maybe_cast
    x = maybe_cast(x, f"conv{n}d")
    weight = maybe_cast(weight, f"conv{n}d")
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    _dimnums(n, channel_last))
    out = lax.conv_general_dilated(
        x, weight,
        window_strides=_tuple(stride, n),
        padding=_padding(padding, n),
        rhs_dilation=_tuple(dilation, n),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, output_size=None):
    """Gradient-of-conv semantics matching paddle's conv_transpose: weight is
    [in_c, out_c/groups, *spatial]."""
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    stride = _tuple(stride, n)
    dilation = _tuple(dilation, n)
    output_padding = _tuple(output_padding, n)
    pad_cfg = _padding(padding, n)
    if isinstance(pad_cfg, str):
        raise NotImplementedError("SAME/VALID for conv_transpose: use ints")

    # lax.conv_transpose with transpose_kernel=True expects weight [i, o, ...]
    # laid out as IO+spatial when using the right dimension numbers.
    if channel_last:
        x_spec = "N" + "DHW"[3 - n:] + "C" if n == 3 else ("NHWC" if n == 2 else "NWC")
    else:
        x_spec = "NC" + ("DHW"[3 - n:] if n == 3 else ("HW" if n == 2 else "W"))
    k_spec = "IO" + ("DHW"[3 - n:] if n == 3 else ("HW" if n == 2 else "W"))
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (x_spec, k_spec, x_spec))

    # conv_transpose padding p maps to lax padding (k-1)*d - p on each side
    k_spatial = weight.shape[2:]
    lax_pad = []
    for i in range(n):
        eff_k = (k_spatial[i] - 1) * dilation[i]
        lo = eff_k - pad_cfg[i][0]
        hi = eff_k - pad_cfg[i][1] + output_padding[i]
        lax_pad.append((lo, hi))

    if groups == 1:
        out = lax.conv_transpose(
            x, weight, strides=stride, padding=lax_pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            transpose_kernel=True)
    else:
        # grouped transpose: split, run per group, concat (XLA fuses these)
        ch_axis = x.ndim - 1 if channel_last else 1
        xs = jnp.split(x, groups, axis=ch_axis)
        ws = jnp.split(weight, groups, axis=0)
        outs = [lax.conv_transpose(xi, wi, strides=stride, padding=lax_pad,
                                   rhs_dilation=dilation, dimension_numbers=dn,
                                   transpose_kernel=True)
                for xi, wi in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=ch_axis)
    if bias is not None:
        if channel_last:
            out = out + bias.reshape((1,) * (n + 1) + (-1,))
        else:
            out = out + bias.reshape((1, -1) + (1,) * n)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size)
