"""Vision/sequence functionals: grid_sample, affine_grid, temporal_shift,
sequence_mask, gather_tree, npair_loss.

Reference: python/paddle/nn/functional/vision.py (grid_sample/affine_grid
— the spatial-transformer pair over phi kernels), common.py
(sequence_mask), extension.py (temporal_shift, gather_tree, npair_loss).

TPU-native: bilinear grid sampling is gather + lerp (vectorized, jits);
gather_tree is a reverse lax.scan over beam parents.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["grid_sample", "affine_grid", "temporal_shift", "sequence_mask",
           "gather_tree", "npair_loss"]


def grid_sample(x, grid, mode: str = "bilinear",
                padding_mode: str = "zeros", align_corners: bool = True,
                name=None):
    """x [N, C, H, W]; grid [N, Ho, Wo, 2] in [-1, 1] (xy order).
    Returns [N, C, Ho, Wo]."""
    x = jnp.asarray(x, jnp.float32)
    grid = jnp.asarray(grid, jnp.float32)
    N, C, H, W = x.shape

    def unnorm(coord, size):
        if align_corners:
            return (coord + 1.0) * (size - 1) / 2.0
        return ((coord + 1.0) * size - 1.0) / 2.0

    gx = unnorm(grid[..., 0], W)                   # [N, Ho, Wo]
    gy = unnorm(grid[..., 1], H)
    if padding_mode == "border":
        gx = jnp.clip(gx, 0, W - 1)
        gy = jnp.clip(gy, 0, H - 1)
    elif padding_mode == "reflection":
        def reflect(c, size):
            if size == 1:
                return jnp.zeros_like(c)     # single pixel: no span
            if align_corners:
                # reflect over [0, size-1]
                span = 2.0 * (size - 1)
                c = jnp.abs(jnp.mod(c, span))
                return jnp.minimum(c, span - c)
            # reference boundaries are the pixel EDGES [-0.5, size-0.5]
            span = 2.0 * size
            c = jnp.mod(c + 0.5, span)
            c = jnp.minimum(c, span - c) - 0.5
            return jnp.clip(c, 0, size - 1)
        gx = reflect(gx, W)
        gy = reflect(gy, H)

    if mode == "nearest":
        xi = jnp.clip(jnp.round(gx), 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(jnp.round(gy), 0, H - 1).astype(jnp.int32)
        out = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, yi, xi)
        valid = ((gx >= -0.5) & (gx <= W - 0.5) &
                 (gy >= -0.5) & (gy <= H - 0.5))
        if padding_mode == "zeros":
            out = out * valid[:, None]
        return out

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx1 = gx - x0
    wy1 = gy - y0

    def take(img, yy, xx):
        """img [C,H,W]; integer index maps with zero outside."""
        inside = ((xx >= 0) & (xx < W) & (yy >= 0) & (yy < H))
        xs = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        ys = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        v = img[:, ys, xs]                         # [C, Ho, Wo]
        if padding_mode == "zeros":
            v = v * inside[None]
        return v

    def per_image(img, x0, y0, wx1, wy1):
        v00 = take(img, y0, x0)
        v01 = take(img, y0, x0 + 1)
        v10 = take(img, y0 + 1, x0)
        v11 = take(img, y0 + 1, x0 + 1)
        wx0 = 1 - wx1
        wy0 = 1 - wy1
        return (v00 * (wy0 * wx0)[None] + v01 * (wy0 * wx1)[None]
                + v10 * (wy1 * wx0)[None] + v11 * (wy1 * wx1)[None])

    return jax.vmap(per_image)(x, x0, y0, wx1, wy1)


def affine_grid(theta, out_shape: Sequence[int], align_corners: bool = True,
                name=None):
    """theta [N, 2, 3]; out_shape [N, C, H, W] -> grid [N, H, W, 2]."""
    theta = jnp.asarray(theta, jnp.float32)
    N, _, H, W = (int(s) for s in out_shape)

    def lin(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        half = 1.0 - 1.0 / size
        return jnp.linspace(-half, half, size)

    ys = lin(H)
    xs = lin(W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")    # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)       # [H, W, 3]
    return jnp.einsum("nij,hwj->nhwi", theta, base)  # [N, H, W, 2]


def temporal_shift(x, seg_num: int, shift_ratio: float = 0.25,
                   data_format: str = "NCHW", name=None):
    """Reference: TSM temporal shift.  x [N*T, C, H, W] (or NHWC)."""
    x = jnp.asarray(x)
    if data_format == "NHWC":
        out = temporal_shift(jnp.transpose(x, (0, 3, 1, 2)), seg_num,
                             shift_ratio, "NCHW")
        return jnp.transpose(out, (0, 2, 3, 1))
    if data_format != "NCHW":
        raise ValueError(f"bad data_format {data_format!r}")
    NT, C, H, W = x.shape
    T = seg_num
    Nb = NT // T
    v = x.reshape(Nb, T, C, H, W)
    fold = int(C * shift_ratio)
    left = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])],
                           axis=1)
    right = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                             v[:, :-1, fold:2 * fold]], axis=1)
    rest = v[:, :, 2 * fold:]
    out = jnp.concatenate([left, right, rest], axis=2)
    return out.reshape(NT, C, H, W)


def sequence_mask(lengths, maxlen: Optional[int] = None, dtype="int64",
                  name=None):
    """Reference: mask [..., maxlen] with 1 where pos < length."""
    lengths = jnp.asarray(lengths)
    if maxlen is None:
        import numpy as _np
        maxlen = int(_np.asarray(jax.device_get(jnp.max(lengths))))
    pos = jnp.arange(maxlen)
    mask = pos[None, :] < lengths[..., None]
    return mask.astype(dtype)


def gather_tree(ids, parents):
    """Reference: beam-search finalize — walk parent pointers backward.
    ids/parents [T, B, beam] -> full sequences [T, B, beam]."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T = ids.shape[0]

    def step(beam_idx, t):
        # beam_idx [B, beam]: which beam each final hypothesis sits on at
        # step t+1; walk to step t
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=-1)
        parent = jnp.take_along_axis(parents[t], beam_idx, axis=-1)
        return parent, tok

    last = jnp.broadcast_to(jnp.arange(ids.shape[-1]), ids.shape[1:])
    _, toks = jax.lax.scan(step, last, jnp.arange(T), reverse=True)
    return toks


def npair_loss(anchor, positive, labels, l2_reg: float = 0.002, name=None):
    """Reference: paddle.nn.functional.npair_loss (NIPS16 N-pair loss)."""
    anchor = jnp.asarray(anchor, jnp.float32)
    positive = jnp.asarray(positive, jnp.float32)
    labels = jnp.asarray(labels).reshape(-1)
    sim = anchor @ positive.T                       # [B, B]
    same = (labels[:, None] == labels[None, :]).astype(jnp.float32)
    same = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(same * logp, axis=1))
    # reference Beta = 0.25 on the summed squared norms
    reg = 0.25 * l2_reg * (jnp.mean(jnp.sum(anchor ** 2, 1))
                           + jnp.mean(jnp.sum(positive ** 2, 1)))
    return ce + reg
