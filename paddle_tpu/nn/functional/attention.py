"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py —
scaled_dot_product_attention / flash_attention routing to the CUDA
flash-attn-2 kernels (paddle/phi/kernels/gpu/flash_attn_kernel.cu, built by
cmake/external/flashattn.cmake).

TPU-native: the default path is a pure-XLA softmax(QK^T)V which XLA already
executes well for moderate seq; long-seq routes to the Pallas flash kernel
(paddle_tpu/kernels/flash_attention.py) when FLAGS_use_pallas_attention and
the platform is TPU.  Layout is paddle's: [batch, seq, heads, head_dim].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.flags import flags

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sdpa_reference"]


_WARNED = set()


def _warn_once(key: str, msg: str):
    if key not in _WARNED:
        _WARNED.add(key)
        import logging
        logging.getLogger("paddle_tpu").warning(msg)


def _warn_traced_fallback():
    _warn_once("varlen_traced",
               "flash_attn_unpadded: causal varlen with traced, distinct "
               "cu_seqlens cannot prove q/k alignment — using the dense "
               "path; pass assume_aligned=True if the packs match")


def _warn_kernel_fallback(e: Exception):
    _warn_once("varlen_kernel", f"flash_attn_unpadded: Pallas varlen route "
               f"failed ({type(e).__name__}: {e}); using the dense path")


def _causal_mask(sq, sk, dtype):
    i = jnp.arange(sq)[:, None]
    j = jnp.arange(sk)[None, :]
    return jnp.where(j <= i + (sk - sq), 0.0, jnp.finfo(dtype).min)


def sdpa_reference(query, key, value, attn_mask=None, dropout_p: float = 0.0,
                   is_causal: bool = False, scale: Optional[float] = None,
                   training: bool = True):
    """Pure-XLA reference path. q/k/v: [B, S, H, D] (paddle layout)."""
    from ...amp.auto_cast import maybe_cast
    query = maybe_cast(query, "attention")
    key = maybe_cast(key, "attention")
    value = maybe_cast(value, "attention")
    b, sq, h, d = query.shape
    sk = key.shape[1]
    kh = key.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    q = jnp.moveaxis(query, 1, 2)   # [B,H,Sq,D]
    k = jnp.moveaxis(key, 1, 2)
    v = jnp.moveaxis(value, 1, 2)
    if kh != h:  # grouped-query attention: repeat kv heads
        rep = h // kh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        logits = logits + _causal_mask(sq, sk, jnp.float32)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, jnp.finfo(jnp.float32).min)
        else:
            logits = logits + attn_mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_p > 0.0 and training:
        from .common import dropout as _dropout
        probs = _dropout(probs, p=dropout_p, training=True)
    probs = probs.astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.moveaxis(out, 1, 2)  # back to [B,S,H,D]


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p: float = 0.0, is_causal: bool = False,
                                 training: bool = True, name=None):
    """Parity: paddle F.scaled_dot_product_attention ([B,S,H,D] layout).

    Routes to the Pallas TPU flash kernel when profitable, else pure XLA.
    """
    from ...kernels.routing import use_pallas as _route
    use_pallas = (
        flags.use_pallas_attention
        and attn_mask is None
        and dropout_p == 0.0
        and _route("flash_attention", seq_q=query.shape[1],
                   seq_k=key.shape[1])
        and query.shape[-1] in (64, 128, 256)
        and jax.default_backend() not in ("cpu",)
    )
    if use_pallas:
        try:
            from ...kernels.flash_attention import flash_attention as _pallas_fa
            return _pallas_fa(query, key, value, causal=is_causal)
        except Exception:
            pass  # fall back to XLA path (e.g. unsupported shape/platform)
    return sdpa_reference(query, key, value, attn_mask, dropout_p, is_causal,
                          training=training)


def flash_attention(query, key, value, dropout: float = 0.0,
                    causal: bool = False, return_softmax: bool = False,
                    fixed_seed_offset=None, rng_name: str = "", training=True,
                    name=None):
    """Parity: paddle F.flash_attention.flash_attention -> (out, softmax)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training=training)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale: float,
                        dropout: float = 0.0, causal: bool = False,
                        return_softmax: bool = False, name=None,
                        assume_aligned: Optional[bool] = None):
    """Varlen API parity: total-token packed layout [T, H, D] with
    cu_seqlens.  Routes to the segment-masked Pallas flash kernel
    (kernels/flash_attention.py — flash_attention_varlen) when the flag
    allows, padding T to a lane multiple with an unmatched segment id;
    dense segment-masked path otherwise (the test oracle)."""
    t, h, d = query.shape
    tk = key.shape[0]
    seg_q = jnp.cumsum(jnp.zeros(t, jnp.int32).at[cu_seqlens_q[1:-1]].add(1))
    seg_k = jnp.cumsum(jnp.zeros(tk, jnp.int32).at[cu_seqlens_k[1:-1]].add(1))
    # kernel route: global-causal ∧ same-segment == per-segment causal only
    # when q/k packs are aligned (self-attention) — gate causal cross packs
    # onto the dense path.  Alignment check: value equality when both
    # cu_seqlens are concrete, object identity under trace.
    def _aligned():
        if not causal:
            return True
        if assume_aligned is not None:
            # explicit caller contract (extension kwarg): under jit the
            # values are traced and alignment is unprovable here
            return bool(assume_aligned) and t == tk
        if t != tk:
            return False
        if cu_seqlens_q is cu_seqlens_k:
            return True
        try:
            import numpy as _np
            return bool(_np.array_equal(_np.asarray(cu_seqlens_q),
                                        _np.asarray(cu_seqlens_k)))
        except Exception:
            # traced, distinct arrays: fall back to the dense path, but
            # say so once — callers who KNOW q/k packs match should pass
            # assume_aligned=True to keep the kernel route under jit
            _warn_traced_fallback()
            return False

    kernel_ok = (
        flags.use_pallas_attention
        and dropout == 0.0
        and d in (64, 128, 256)
        and jax.default_backend() not in ("cpu",)   # dense XLA wins on CPU
        and _aligned())
    if kernel_ok:
        try:
            from ...kernels.flash_attention import flash_attention_varlen
            pad_q = (-t) % 128
            pad_k = (-tk) % 128
            qp = jnp.pad(query, [(0, pad_q), (0, 0), (0, 0)])
            kp = jnp.pad(key, [(0, pad_k), (0, 0), (0, 0)])
            vp = jnp.pad(value, [(0, pad_k), (0, 0), (0, 0)])
            # padding rows: ids that match nothing real (nor each other)
            sq = jnp.pad(seg_q, (0, pad_q), constant_values=-1)[None]
            sk_ = jnp.pad(seg_k, (0, pad_k), constant_values=-2)[None]
            out = flash_attention_varlen(qp[None], kp[None], vp[None], sq,
                                         sk_, causal=causal, scale=scale)[0]
            return out[:t], None
        except Exception as e:
            # fall back to the dense path for robustness, but never
            # silently: a broken kernel masquerading as a perf regression
            # is undiagnosable
            _warn_kernel_fallback(e)
    logits = jnp.einsum("qhd,khd->hqk", query, key,
                        preferred_element_type=jnp.float32) * scale
    mask = seg_q[:, None] == seg_k[None, :]
    if causal:
        pos_q = jnp.arange(t) - jnp.take(cu_seqlens_q, seg_q)
        pos_k = jnp.arange(tk) - jnp.take(cu_seqlens_k, seg_k)
        mask = mask & (pos_k[None, :] <= pos_q[:, None])
    logits = jnp.where(mask[None], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(value.dtype)
    out = jnp.einsum("hqk,khd->qhd", probs, value)
    return (out, None)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """CSR-masked attention (reference: F.sparse_attention,
    sparse_attention_op): softmax runs only over each query row's CSR
    column set.  q/k/v [B, H, S, D]; offset [B, H, S+1]; columns
    [B, H, nnz].

    TPU-native: static-shape mask materialization + dense MXU matmuls —
    on TPU the structured-sparsity win comes from blockwise masking
    inside the flash kernel (flash_attention_varlen covers the varlen
    case); this op exists for API/semantics parity at CSR granularity.
    """
    q = jnp.asarray(query).astype(jnp.float32)
    k = jnp.asarray(key).astype(jnp.float32)
    v = jnp.asarray(value).astype(jnp.float32)
    b, h, s, d = q.shape
    off = jnp.asarray(sparse_csr_offset).reshape(b * h, s + 1)
    cols = jnp.asarray(sparse_csr_columns).reshape(b * h, -1)
    nnz = cols.shape[-1]

    def row_mask(off_i, cols_i):
        rows = jnp.searchsorted(off_i, jnp.arange(nnz),
                                side="right") - 1
        rows = jnp.clip(rows, 0, s - 1)
        valid = jnp.arange(nnz) < off_i[-1]
        m = jnp.zeros((s, s), bool)
        return m.at[rows, cols_i].max(valid)

    mask = jax.vmap(row_mask)(off, cols).reshape(b, h, s, s)
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / (d ** 0.5)
    if attn_mask is not None:
        scores = scores + jnp.asarray(attn_mask)
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask).astype(bool)
        mask = mask & kp[:, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    p = jnp.where(mask, p, 0.0)   # rows with empty column sets -> 0
    out = jnp.einsum("bhst,bhtd->bhsd", p, v)
    return out.astype(jnp.asarray(query).dtype)


__all__ += ["sparse_attention"]
