"""paddle_tpu.nn.functional — functional op surface (parity:
python/paddle/nn/functional/)."""

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .input import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403

from . import activation, common, conv, norm, pooling, loss, input, attention  # noqa: F401
from .vision import *  # noqa: F401,F403


# reference exposes diag_embed at F as well as paddle top level
from ...tensor.manipulation import diag_embed  # noqa: E402,F401
