"""Pooling functionals via lax.reduce_window.

Reference: python/paddle/nn/functional/pooling.py — max_pool1d/2d/3d,
avg_pool*, adaptive_*_pool*, global pooling.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d",
           "avg_pool2d", "avg_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v * n if len(v) == 1 else v


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == n:
            return [(p, p) for p in padding]
        if len(padding) == 2 * n:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _pool(x, n, kernel, stride, padding, kind, ceil_mode=False,
          exclusive=True, data_format="NCHW"):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_cfg(padding, n)
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        spatial_dims = tuple(range(1, n + 1))
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        spatial_dims = tuple(range(2, n + 2))
    if isinstance(pad, str):
        pad_all = pad
    else:
        full = [(0, 0)] * x.ndim
        for i, d in enumerate(spatial_dims):
            full[d] = pad[i]
        if ceil_mode:
            # extend upper padding so last partial window is included
            for i, d in enumerate(spatial_dims):
                size = x.shape[d] + full[d][0] + full[d][1]
                rem = (size - kernel[i]) % stride[i]
                if rem != 0:
                    full[d] = (full[d][0], full[d][1] + stride[i] - rem)
        pad_all = full

    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pad_all)
    # avg
    summed = lax.reduce_window(x, 0.0, lax.add, window,
                               strides, pad_all)
    if exclusive and pad_all != "VALID" and not isinstance(pad_all, str):
        ones = jnp.ones_like(x)
        count = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad_all)
        return summed / count
    denom = float(np.prod(kernel))
    return summed / denom


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    out = _pool(x, 1, kernel_size, stride, padding, "max", ceil_mode, data_format=df)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, 2, kernel_size, stride, padding, "max", ceil_mode,
                 data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, 3, kernel_size, stride, padding, "max", ceil_mode,
                 data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, 1, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, df)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    out = _pool(x, 2, kernel_size, stride, padding, "avg", ceil_mode,
                exclusive, data_format)
    if divisor_override is not None:
        k = _tuple(kernel_size, 2)
        out = out * (float(np.prod(k)) / divisor_override)
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, 3, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format)


def _adaptive(x, output_size, n, kind, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    output_size = _tuple(output_size, n)
    spatial_dims = tuple(range(1, n + 1)) if channel_last else tuple(range(2, n + 2))
    out = x
    for i, d in enumerate(spatial_dims):
        osz = output_size[i]
        if osz is None:
            continue
        isz = out.shape[d]
        if isz % osz == 0:
            k = isz // osz
            window = [1] * out.ndim
            strides = [1] * out.ndim
            window[d] = k
            strides[d] = k
            if kind == "max":
                out = lax.reduce_window(out, -jnp.inf, lax.max, tuple(window),
                                        tuple(strides), "VALID")
            else:
                out = lax.reduce_window(out, 0.0, lax.add, tuple(window),
                                        tuple(strides), "VALID") / k
        else:
            # general adaptive: gather per output bin (torch-style bins)
            starts = (np.arange(osz) * isz) // osz
            ends = -(-((np.arange(osz) + 1) * isz) // osz)
            slices = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[d] = slice(int(s), int(e))
                seg = out[tuple(sl)]
                red = jnp.max(seg, axis=d, keepdims=True) if kind == "max" \
                    else jnp.mean(seg, axis=d, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=d)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", "NCDHW")
