"""Pooling functionals via lax.reduce_window.

Reference: python/paddle/nn/functional/pooling.py — max_pool1d/2d/3d,
avg_pool*, adaptive_*_pool*, global pooling.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["max_pool1d", "max_pool2d", "max_pool3d", "avg_pool1d",
           "avg_pool2d", "avg_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d"]


def _tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v * n if len(v) == 1 else v


def _pad_cfg(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == n:
            return [(p, p) for p in padding]
        if len(padding) == 2 * n:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    return [tuple(p) for p in padding]


def _pool(x, n, kernel, stride, padding, kind, ceil_mode=False,
          exclusive=True, data_format="NCHW"):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_cfg(padding, n)
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        spatial_dims = tuple(range(1, n + 1))
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        spatial_dims = tuple(range(2, n + 2))
    if isinstance(pad, str):
        pad_all = pad
    else:
        full = [(0, 0)] * x.ndim
        for i, d in enumerate(spatial_dims):
            full[d] = pad[i]
        if ceil_mode:
            # extend upper padding so last partial window is included
            for i, d in enumerate(spatial_dims):
                size = x.shape[d] + full[d][0] + full[d][1]
                rem = (size - kernel[i]) % stride[i]
                if rem != 0:
                    full[d] = (full[d][0], full[d][1] + stride[i] - rem)
        pad_all = full

    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pad_all)
    # avg
    summed = lax.reduce_window(x, 0.0, lax.add, window,
                               strides, pad_all)
    if exclusive and pad_all != "VALID" and not isinstance(pad_all, str):
        ones = jnp.ones_like(x)
        count = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad_all)
        return summed / count
    denom = float(np.prod(kernel))
    return summed / denom


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        if data_format == "NLC":
            raise ValueError("return_mask needs channel-first layout")
        return _max_pool_with_mask(x, 1, kernel_size, stride, padding,
                                   ceil_mode)
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, 1, kernel_size, stride, padding, "max", ceil_mode,
                 data_format=df)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("return_mask needs channel-first layout")
        return _max_pool_with_mask(x, 2, kernel_size, stride, padding,
                                   ceil_mode)
    return _pool(x, 2, kernel_size, stride, padding, "max", ceil_mode,
                 data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if data_format != "NCDHW":
            raise ValueError("return_mask needs channel-first layout")
        return _max_pool_with_mask(x, 3, kernel_size, stride, padding,
                                   ceil_mode)
    return _pool(x, 3, kernel_size, stride, padding, "max", ceil_mode,
                 data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool(x, 1, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, df)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    out = _pool(x, 2, kernel_size, stride, padding, "avg", ceil_mode,
                exclusive, data_format)
    if divisor_override is not None:
        k = _tuple(kernel_size, 2)
        out = out * (float(np.prod(k)) / divisor_override)
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, 3, kernel_size, stride, padding, "avg", ceil_mode,
                 exclusive, data_format)


def _adaptive(x, output_size, n, kind, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    output_size = _tuple(output_size, n)
    spatial_dims = tuple(range(1, n + 1)) if channel_last else tuple(range(2, n + 2))
    out = x
    for i, d in enumerate(spatial_dims):
        osz = output_size[i]
        if osz is None:
            continue
        isz = out.shape[d]
        if isz % osz == 0:
            k = isz // osz
            window = [1] * out.ndim
            strides = [1] * out.ndim
            window[d] = k
            strides[d] = k
            if kind == "max":
                out = lax.reduce_window(out, -jnp.inf, lax.max, tuple(window),
                                        tuple(strides), "VALID")
            else:
                out = lax.reduce_window(out, 0.0, lax.add, tuple(window),
                                        tuple(strides), "VALID") / k
        else:
            # general adaptive: gather per output bin (torch-style bins)
            starts = (np.arange(osz) * isz) // osz
            ends = -(-((np.arange(osz) + 1) * isz) // osz)
            slices = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[d] = slice(int(s), int(e))
                seg = out[tuple(sl)]
                red = jnp.max(seg, axis=d, keepdims=True) if kind == "max" \
                    else jnp.mean(seg, axis=d, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=d)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", "NCDHW")


# --- round-3 additions: max-pool argmax masks + max_unpool family --------
# (reference: paddle/phi unpool kernels; mask = flat index into the input
# spatial map, exactly what max_poolXd(return_mask=True) hands out)

def _max_pool_with_mask(x, n, kernel, stride, padding, ceil_mode=False):
    """Channel-first max pool returning (out, mask).  Pads with the dtype
    minimum and extracts windows via conv_general_dilated_patches so the
    argmax is taken over real elements only."""
    kernel = _tuple(kernel, n)
    stride = _tuple(stride if stride is not None else kernel, n)
    pad = _pad_cfg(padding, n)
    if isinstance(pad, str):
        raise ValueError("return_mask needs explicit int padding")
    pad = [tuple(pp) for pp in pad]
    if ceil_mode:
        # extend upper padding so the last partial window is included
        # (same rule as _pool's ceil_mode branch)
        for i in range(n):
            size = x.shape[2 + i] + pad[i][0] + pad[i][1]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                pad[i] = (pad[i][0], pad[i][1] + stride[i] - rem)
    # finite minimum, NOT -inf: patch extraction lowers to a conv and
    # -inf * 0 = nan would poison padded windows
    neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype) if \
        jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    xp = jnp.pad(x, [(0, 0), (0, 0)] + list(pad), constant_values=neg)
    patches = lax.conv_general_dilated_patches(
        xp, filter_shape=kernel, window_strides=stride,
        padding=[(0, 0)] * n)
    N, C = x.shape[0], x.shape[1]
    OS = patches.shape[2:]
    K = int(np.prod(kernel))
    pat = patches.reshape((N, C, K) + OS)
    out = jnp.max(pat, axis=2)
    loc = jnp.argmax(pat, axis=2)          # local flat idx within window
    S = x.shape[2:]
    rem = loc
    flat_global = jnp.zeros_like(loc)
    for i in range(n):
        kprod = int(np.prod(kernel[i + 1:]))
        ki = rem // kprod
        rem = rem % kprod
        origin = jnp.arange(OS[i]) * stride[i] - pad[i][0]
        shape = [1] * (2 + n)
        shape[2 + i] = OS[i]
        gi = ki + origin.reshape(shape)
        flat_global = flat_global * S[i] + gi
    return out, flat_global.astype(jnp.int32)


def _unpool_out_size(in_size, kernel, stride, pad):
    return (in_size - 1) * stride - 2 * pad + kernel


def _max_unpool(x, indices, n, kernel_size, stride, padding, output_size,
                data_format):
    if not data_format.startswith("NC"):
        raise ValueError("max_unpool supports channel-first only "
                         "(reference restriction)")
    kernel = _tuple(kernel_size, n)
    stride_t = _tuple(stride if stride is not None else kernel_size, n)
    pad = _tuple(padding, n)
    N, C = x.shape[0], x.shape[1]
    if output_size is None:
        out_s = tuple(_unpool_out_size(x.shape[2 + i], kernel[i],
                                       stride_t[i], pad[i])
                      for i in range(n))
    else:
        out_s = tuple(output_size)[-n:]
    total = int(np.prod(out_s))
    vals = x.reshape(N, C, -1)
    idx = jnp.asarray(indices).reshape(N, C, -1).astype(jnp.int32)
    flat = jnp.zeros((N, C, total), x.dtype)
    flat = flat.at[jnp.arange(N)[:, None, None],
                   jnp.arange(C)[None, :, None], idx].set(vals)
    return flat.reshape((N, C) + out_s)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)


__all__ += ["max_unpool1d", "max_unpool2d", "max_unpool3d"]


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Reference: paddle.nn.functional.lp_pool1d — power-average pooling:
    (sum x^p over the window)^(1/p), pads contributing 0 (reference
    semantics: sum WITHOUT abs; negative sums at odd/fractional p yield
    NaN exactly as torch/paddle do); p=inf degenerates to max pool."""
    p = float(norm_type)
    if p == float("inf"):
        return max_pool1d(x, kernel_size, stride, padding,
                          ceil_mode=ceil_mode, data_format=data_format)
    k = _tuple(kernel_size, 1)
    pow_x = x ** p
    # exclusive=False: divide by the FULL kernel size so avg*prod(k)
    # recovers the exact window sum even for padded/partial windows
    # (review r4: exclusive=True over-counted border windows)
    avg = avg_pool1d(pow_x, kernel_size, stride, padding, exclusive=False,
                     ceil_mode=ceil_mode, data_format=data_format)
    return (avg * float(np.prod(k))) ** (1.0 / p)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """Reference: paddle.nn.functional.lp_pool2d (see lp_pool1d)."""
    p = float(norm_type)
    if p == float("inf"):
        return max_pool2d(x, kernel_size, stride, padding,
                          ceil_mode=ceil_mode, data_format=data_format)
    k = _tuple(kernel_size, 2)
    pow_x = x ** p
    avg = avg_pool2d(pow_x, kernel_size, stride, padding,
                     ceil_mode=ceil_mode, exclusive=False,
                     data_format=data_format)
    return (avg * float(np.prod(k))) ** (1.0 / p)


def _fractional_boundaries(n_in, n_out, u):
    """Graham's pseudo-random pooling boundaries: region i spans
    [ceil(alpha*(i+u)) - ceil(alpha*u), ...) with alpha = n_in/n_out —
    the reference op's index sequence (deterministic given u)."""
    alpha = n_in / n_out
    idx = np.ceil(alpha * (np.arange(n_out + 1) + u)).astype(np.int64)
    idx = idx - idx[0]
    idx = np.clip(idx, 0, n_in)
    idx[-1] = n_in
    return idx


def _fractional_max(x, axes_sizes, output_size, u):
    """Max over fractional regions along the trailing spatial axes of a
    channel-first tensor [N, C, *spatial]."""
    spatial = len(axes_sizes)
    out = x
    for d in range(spatial):
        n_in = axes_sizes[d]
        n_out = output_size[d]
        bounds = _fractional_boundaries(n_in, n_out, u)
        axis = 2 + d
        slabs = []
        for i in range(n_out):
            lo, hi = int(bounds[i]), int(max(bounds[i + 1], bounds[i] + 1))
            sl = [slice(None)] * out.ndim
            sl[axis] = slice(lo, hi)
            slabs.append(jnp.max(out[tuple(sl)], axis=axis, keepdims=True))
        out = jnp.concatenate(slabs, axis=axis)
    return out


def _fractional_max_pool(x, output_size, kernel_size, random_u,
                         return_mask, rank):
    """Shared core of fractional_max_pool2d/3d (Graham, 'Fractional
    Max-Pooling').  ``random_u`` pins the pseudo-random offset; None
    draws one from the framework RNG.  Documented cuts (also recorded in
    OP_COVERAGE's explicit-cuts table): return_mask=True (XLA would
    materialize argmax maps) and explicit kernel_size (the reference
    pools OVERLAPPING [start, start+k) windows; this implementation
    pools the disjoint boundary regions — raising beats silently
    returning different numbers)."""
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool(return_mask=True) is not supported")
    if kernel_size is not None:
        raise NotImplementedError(
            "fractional_max_pool with an explicit kernel_size pools "
            "overlapping windows in the reference; only the disjoint "
            "region form (kernel_size=None) is implemented")
    if random_u is None:
        from ...framework.random import next_rng_key
        import jax as _jax
        random_u = float(_jax.random.uniform(next_rng_key(), ()))
    elif not 0.0 <= float(random_u) < 1.0:
        raise ValueError(
            f"fractional_max_pool random_u must be in [0, 1), got "
            f"{random_u} (the reference validates the same range; an "
            f"out-of-range offset would silently shift every region)")
    output_size = _tuple(output_size, rank)
    sizes = x.shape[2:2 + rank]
    for n_in, n_out in zip(sizes, output_size):
        if n_out > n_in:
            raise ValueError(
                f"fractional_max_pool output_size {output_size} must not "
                f"exceed the input spatial size {tuple(sizes)}")
    return _fractional_max(x, sizes, output_size, float(random_u))


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Reference: paddle.nn.functional.fractional_max_pool2d (see
    _fractional_max_pool for the documented cuts)."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Reference: paddle.nn.functional.fractional_max_pool3d."""
    return _fractional_max_pool(x, output_size, kernel_size, random_u,
                                return_mask, 3)


__all__ += ["lp_pool1d", "lp_pool2d", "fractional_max_pool2d",
            "fractional_max_pool3d"]
