"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All are thin jnp/lax expressions — XLA fuses them into adjacent matmuls on
TPU, which is exactly what the reference needs hand-written CUDA epilogues
for (paddle/phi/kernels/fusion/ — fused bias+act epilogues).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "relu", "relu6", "relu_", "gelu", "silu", "swish", "sigmoid", "tanh",
    "softmax", "log_softmax", "leaky_relu", "leaky_relu_", "elu", "elu_", "selu", "celu",
    "hardswish", "hardsigmoid", "hardtanh", "hardshrink", "softshrink",
    "tanhshrink", "softplus", "softsign", "mish", "glu", "swiglu",
    "prelu", "rrelu", "maxout", "thresholded_relu", "log_sigmoid",
    "gumbel_softmax",
]


def relu(x):
    return jax.nn.relu(x)


relu_ = relu  # in-place alias for parity; arrays are immutable here


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def gelu(x, approximate: bool = False):
    return jax.nn.gelu(x, approximate=approximate)


def silu(x):
    return jax.nn.silu(x)


def swish(x):
    return jax.nn.silu(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def softmax(x, axis: int = -1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis: int = -1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


def leaky_relu(x, negative_slope: float = 0.01):
    return jax.nn.leaky_relu(x, negative_slope=negative_slope)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha=alpha)


def selu(x, scale: float = 1.0507009873554805, alpha: float = 1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def celu(x, alpha: float = 1.0):
    return jax.nn.celu(x, alpha=alpha)


def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def hardsigmoid(x, slope: float = 1.0 / 6, offset: float = 0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


def hardtanh(x, min: float = -1.0, max: float = 1.0):
    return jnp.clip(x, min, max)


def hardshrink(x, threshold: float = 0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def softshrink(x, threshold: float = 0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def tanhshrink(x):
    return x - jnp.tanh(x)


def softplus(x, beta: float = 1.0, threshold: float = 20.0):
    bx = beta * x
    return jnp.where(bx > threshold, x, jnp.logaddexp(bx, 0.0) / beta)


def softsign(x):
    return jax.nn.soft_sign(x)


def mish(x):
    return x * jnp.tanh(softplus(x))


def glu(x, axis: int = -1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def swiglu(x, y=None):
    """paddle.incubate.nn.functional.swiglu parity: silu(x) * y (y defaults
    to the second half of x)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


def prelu(x, weight):
    w = jnp.asarray(weight)
    if w.ndim == 1 and w.shape[0] > 1:
        shape = [1] * x.ndim
        shape[1] = w.shape[0]  # NCHW channel dim, paddle default
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


def rrelu(x, lower: float = 1.0 / 8, upper: float = 1.0 / 3, training: bool = False):
    if training:
        from ...framework.random import next_rng_key
        a = jax.random.uniform(next_rng_key(), x.shape, dtype=x.dtype,
                               minval=lower, maxval=upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, a * x)


def thresholded_relu(x, threshold: float = 1.0):
    return jnp.where(x > threshold, x, 0.0)


def maxout(x, groups: int, axis: int = 1):
    axis = axis % x.ndim
    c = x.shape[axis]
    if c % groups:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def gumbel_softmax(x, temperature: float = 1.0, hard: bool = False, axis: int = -1):
    from ...framework.random import next_rng_key
    g = jax.random.gumbel(next_rng_key(), x.shape, dtype=x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        # straight-through: forward = one-hot, backward = soft
        y = jax.lax.stop_gradient(y_hard - y) + y
    return y


def elu_(x, alpha: float = 1.0):
    """Inplace-named elu (reference: F.elu_); returns the result."""
    return elu(x, alpha)


def leaky_relu_(x, negative_slope: float = 0.01):
    """Inplace-named leaky_relu (reference: F.leaky_relu_)."""
    return leaky_relu(x, negative_slope)
