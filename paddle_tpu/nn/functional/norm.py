"""Normalization functionals.

Reference: python/paddle/nn/functional/norm.py — batch_norm, layer_norm,
instance_norm, group_norm, normalize; incubate rms_norm.  XLA fuses these
into surrounding ops on TPU (the reference needs
fused_bias_dropout_residual_layer_norm CUDA kernels for the same effect —
paddle/phi/kernels/fusion/gpu).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "normalize", "rms_norm", "local_response_norm"]


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon: float = 1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    # fused Pallas path: the common last-dim affine case on TPU (one VPU
    # pass, no HBM intermediates).  Constraints keep it strictly better
    # than XLA: dtype-preserving params (no public dtype change vs the
    # promoting XLA path), lane-aligned h bounded for VMEM, and row
    # counts that tile into real blocks (no degenerate 1-row grids).
    h_last = x.shape[-1]
    rows = x.size // h_last if h_last else 0
    if (len(axes) == 1 and axes[0] == x.ndim - 1 and weight is not None
            and bias is not None and h_last % 128 == 0 and h_last <= 8192
            and rows and rows % 8 == 0
            and getattr(weight, "dtype", None) == x.dtype
            and getattr(bias, "dtype", None) == x.dtype):
        from ...core.flags import flags as _flags
        from ...kernels.routing import use_pallas as _route
        if (_flags.use_pallas_norm and _on_tpu()
                and _route("layer_norm", rows=rows, h=h_last)):
            try:
                import paddle_tpu.kernels as _k
                return _k.fused_layer_norm_pallas(x, weight, bias,
                                                  epsilon, interpret=False)
            except Exception:
                pass   # fall back to the XLA form (same pattern as sdpa)
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.float16, jnp.bfloat16) else x
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


def rms_norm(x, weight=None, bias=None, epsilon: float = 1e-6, begin_norm_axis: int = -1):
    """paddle.incubate.nn.functional.rms_norm parity (Llama-family norm)."""
    axes = tuple(range(begin_norm_axis % x.ndim, x.ndim)) if begin_norm_axis != -1 else (-1,)
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.float16, jnp.bfloat16) else x
    ms = jnp.mean(jnp.square(x32), axis=axes, keepdims=True)
    y = (x32 * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        y = y * weight
    if bias is not None:
        y = y + bias
    return y


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, data_format: str = "NCHW",
               use_global_stats: Optional[bool] = None, name=None):
    """Returns (y, new_running_mean, new_running_var) when training else y.

    NOTE deviation from the reference's in-place running-stat mutation: the
    functional form returns updated stats; nn.BatchNorm layers write them
    into buffers so functional_call captures them.
    """
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = tuple(x.shape[ch_axis] if i == ch_axis else 1 for i in range(x.ndim))

    use_stats = (not training) if use_global_stats is None else use_global_stats
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.float16, jnp.bfloat16) else x
    if use_stats:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    else:
        mean = jnp.mean(x32, axis=reduce_axes)
        var = jnp.mean(jnp.square(x32), axis=reduce_axes) - jnp.square(mean)
        # paddle momentum semantics: r = m*r + (1-m)*batch
        new_rm = momentum * running_mean + (1 - momentum) * mean
        n = x.size / x.shape[ch_axis]
        unbiased = var * (n / max(n - 1, 1))
        new_rv = momentum * running_var + (1 - momentum) * unbiased
    y = (x32 - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * weight.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    if training and not use_stats:
        return y, new_rm, new_rv
    return y


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats: bool = True, momentum: float = 0.9,
                  eps: float = 1e-5, data_format: str = "NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    spatial = tuple(i for i in range(x.ndim) if i not in (0, ch_axis))
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.float16, jnp.bfloat16) else x
    mean = jnp.mean(x32, axis=spatial, keepdims=True)
    var = jnp.var(x32, axis=spatial, keepdims=True)
    y = ((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    bshape = tuple(x.shape[ch_axis] if i == ch_axis else 1 for i in range(x.ndim))
    if weight is not None:
        y = y * weight.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return y


def group_norm(x, num_groups: int, epsilon: float = 1e-5, weight=None,
               bias=None, data_format: str = "NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if channel_last:
        x_t = jnp.moveaxis(x, -1, 1)
        y = group_norm(x_t, num_groups, epsilon, weight, bias, "NCHW")
        return jnp.moveaxis(y, 1, -1)
    n, c = x.shape[:2]
    g = num_groups
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.float16, jnp.bfloat16) else x
    xg = x32.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape).astype(x.dtype)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if weight is not None:
        y = y * weight.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return y


def normalize(x, p: float = 2, axis: int = 1, epsilon: float = 1e-12, name=None):
    if p == 2:
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        norm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(norm, epsilon)


def local_response_norm(x, size: int, alpha: float = 1e-4, beta: float = 0.75,
                        k: float = 1.0, data_format: str = "NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    sq = jnp.square(x)
    half = size // 2
    pad_cfg = [(0, 0)] * x.ndim
    pad_cfg[ch_axis] = (half, size - half - 1)
    sq = jnp.pad(sq, pad_cfg)
    # sliding sum over channel axis
    idx = [slice(None)] * x.ndim
    acc = jnp.zeros_like(x)
    for i in range(size):
        idx[ch_axis] = slice(i, i + x.shape[ch_axis])
        acc = acc + sq[tuple(idx)]
    return x / ((k + alpha * acc) ** beta)
