"""Common functionals: linear, dropout, padding, interpolate, fold/unfold...

Reference: python/paddle/nn/functional/common.py — linear, dropout, pad,
interpolate, ... (SURVEY.md §2.2 "Functional").
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ...framework.random import next_rng_key

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "feature_alpha_dropout",
           "pad", "interpolate", "upsample", "bilinear", "cosine_similarity",
           "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
           "label_smooth", "unfold", "fold", "zeropad2d"]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle's weight layout W:[in, out].

    On TPU this is the MXU primitive; keep inputs bf16-batched and XLA fuses
    the bias add (the reference needs cuBLASLt epilogues for that —
    paddle/phi/kernels/fusion — fused_linear).
    """
    from ...amp.auto_cast import maybe_cast
    x = maybe_cast(x, "linear")
    weight = maybe_cast(weight, "linear")
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


def dropout(x, p: float = 0.5, axis=None, training: bool = True,
            mode: str = "upscale_in_train", name=None, rng_key=None):
    """Parity: paddle F.dropout incl. the legacy 'downscale_in_infer' mode."""
    if p == 0.0 or not training:
        if mode == "downscale_in_infer" and not training:
            return x * (1 - p)
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    if rng_key is None:
        from ...framework.random import has_rng_context
        import jax.core as _core
        if not has_rng_context() and isinstance(x, _core.Tracer):
            # without a threaded key, the eager generator's concrete key
            # would be baked into the compiled program -> identical mask
            # every step, silently corrupting training
            raise RuntimeError(
                "dropout traced under jit without an RNG context: pass "
                "rng=key to nn.functional_call (or wrap with "
                "paddle_tpu.rng_context(key)) so each step draws a fresh "
                "mask")
    key = rng_key if rng_key is not None else next_rng_key()
    if axis is None:
        mask_shape = x.shape
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        mask_shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x)).astype(x.dtype)
    return jnp.where(keep, x, jnp.zeros_like(x)).astype(x.dtype)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = next_rng_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Reference: alpha dropout over whole CHANNELS ([N, C, ...] — one
    draw per (n, c), SELU-compatible statistics like alpha_dropout)."""
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = next_rng_key()
    mask_shape = x.shape[:2] + (1,) * (x.ndim - 2)
    keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)


def _norm_pad(pad_spec, ndim, data_format):
    """Convert paddle pad spec (flat list, reversed-dims pairs for the
    spatial form) to jnp.pad config."""
    if isinstance(pad_spec, int):
        return [(pad_spec, pad_spec)] * ndim
    pad_spec = list(pad_spec)
    if len(pad_spec) == 2 * ndim:
        # full-form: [(before,after)] per dim in order
        return [(pad_spec[2 * i], pad_spec[2 * i + 1]) for i in range(ndim)]
    # spatial form (e.g. NCHW x with [l, r, t, b]): applies to last spatial
    # dims in reverse order, matching paddle/torch semantics
    n_spatial = len(pad_spec) // 2
    cfg = [(0, 0)] * ndim
    if data_format and data_format.startswith("N") and data_format.endswith("C"):
        spatial_dims = list(range(1, 1 + (ndim - 2)))
    else:
        spatial_dims = list(range(2, ndim))
    for i in range(n_spatial):
        dim = spatial_dims[-(i + 1)]
        cfg[dim] = (pad_spec[2 * i], pad_spec[2 * i + 1])
    return cfg


def pad(x, pad, mode: str = "constant", value: float = 0.0,
        data_format: str = "NCHW", name=None):
    cfg = _norm_pad(pad, x.ndim, data_format)
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode: str = "nearest",
                align_corners: bool = False, align_mode: int = 0,
                data_format: str = "NCHW", name=None):
    """Image resize via jax.image.resize (nearest/bilinear/bicubic/trilinear)."""
    if data_format in ("NCHW", "NCDHW", "NCL", "NCW"):
        spatial = list(x.shape[2:])
        ch_first = True
    else:
        spatial = list(x.shape[1:-1])
        ch_first = False
    if size is None:
        if scale_factor is None:
            raise ValueError("one of size/scale_factor required")
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "trilinear": "linear", "linear": "linear", "area": "linear"}[mode.lower()]
    if ch_first:
        out_shape = x.shape[:2] + tuple(size)
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    return jax.image.resize(x, out_shape, method=method).astype(x.dtype)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[b, o] = x1[b,:] @ W[o] @ x2[b,:] + bias; W: [out, in1, in2]."""
    y = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        y = y + bias
    return y


def cosine_similarity(x1, x2, axis: int = 1, eps: float = 1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def pixel_shuffle(x, upscale_factor: int, data_format="NCHW", name=None):
    r = upscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        oc = c // (r * r)
        x = x.reshape(b, oc, r, r, h, w)
        x = x.transpose(0, 1, 4, 2, 5, 3)
        return x.reshape(b, oc, h * r, w * r)
    b, h, w, c = x.shape
    oc = c // (r * r)
    x = x.reshape(b, h, w, r, r, oc)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h * r, w * r, oc)


def pixel_unshuffle(x, downscale_factor: int, data_format="NCHW", name=None):
    r = downscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = x.reshape(b, c, h // r, r, w // r, r)
        x = x.transpose(0, 1, 3, 5, 2, 4)
        return x.reshape(b, c * r * r, h // r, w // r)
    b, h, w, c = x.shape
    x = x.reshape(b, h // r, r, w // r, r, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // r, w // r, c * r * r)


def channel_shuffle(x, groups: int, data_format="NCHW", name=None):
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = x.reshape(b, groups, c // groups, h, w)
        return x.transpose(0, 2, 1, 3, 4).reshape(b, c, h, w)
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, groups, c // groups)
    return x.transpose(0, 1, 2, 4, 3).reshape(b, h, w, c)


def label_smooth(label, prior_dist=None, epsilon: float = 0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col: [N,C,H,W] -> [N, C*kh*kw, L] (parity: F.unfold)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings) if not (isinstance(paddings, (list, tuple)) and len(paddings) == 4) else (None, None)
    if ph is None:
        pt, pl, pb, pr = paddings
    else:
        pt = pb = ph
        pl = pr = pw
    dh, dw = _pair(dilations)
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding="VALID", rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, oh * ow)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im inverse of unfold (sum of overlapping patches)."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    p = _pair(paddings)
    dh, dw = _pair(dilations)
    n, ckk, l = x.shape
    c = ckk // (kh * kw)
    # scatter-add patches back; use the vjp of unfold for correctness
    def _unfold_fn(img):
        return unfold(img, (kh, kw), (sh, sw), (p[0], p[1]), (dh, dw))
    img_shape = (n, c, oh, ow)
    _, vjp = jax.vjp(_unfold_fn, jnp.zeros(img_shape, x.dtype))
    return vjp(x)[0]


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

def pairwise_distance(x, y, p: float = 2.0, epsilon: float = 1e-6,
                      keepdim: bool = False, name=None):
    """p-norm of x - y along the last dim (reference:
    F.pairwise_distance)."""
    d = jnp.asarray(x) - jnp.asarray(y) + epsilon
    if p == float("inf"):
        out = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
    elif p == 2.0:
        out = jnp.sqrt(jnp.maximum(jnp.sum(d * d, axis=-1,
                                           keepdims=keepdim), 0.0))
    else:
        out = jnp.sum(jnp.abs(d) ** p, axis=-1,
                      keepdims=keepdim) ** (1.0 / p)
    return out


def class_center_sample(label, num_classes: int, num_samples: int,
                        group=None, seed=None, name=None):
    """Sample class centers for partial-FC training (reference:
    class_center_sample op): every positive class is kept, negatives fill
    up to ``num_samples``; returns (remapped_label, sampled_class_center)
    with the sampled centers sorted ascending.  Static shapes: the output
    is always [num_samples].  Fresh negatives are drawn per call from the
    global RNG stream; pass ``seed`` for a deterministic draw.

    The batch must not contain more than ``num_samples`` distinct labels
    (the reference grows its output instead; here shapes are static, so
    overflow raises when detectable eagerly)."""
    import numpy as _np
    import jax as _jax
    lbl = jnp.asarray(label).astype(jnp.int32).reshape(-1)
    if not isinstance(lbl, _jax.core.Tracer):
        n_pos = len(_np.unique(_np.asarray(lbl)))
        if n_pos > num_samples:
            raise ValueError(
                f"batch has {n_pos} distinct classes > num_samples="
                f"{num_samples}; raise num_samples (static-shape output "
                f"cannot grow like the reference's)")
    pos = jnp.zeros((num_classes,), jnp.float32).at[lbl].set(1.0)
    if seed is not None:
        key = _jax.random.PRNGKey(seed)
    else:
        from ...framework.random import next_rng_key
        key = next_rng_key()
    u = _jax.random.uniform(key, (num_classes,))
    score = pos * 2.0 + u            # positives always beat negatives
    _, picked = _jax.lax.top_k(score, num_samples)
    sampled = jnp.sort(picked)
    # remap: position of each label inside the sorted sample
    remapped = jnp.searchsorted(sampled, lbl).astype(jnp.int32)
    return remapped, sampled


__all__ += ["pairwise_distance", "class_center_sample"]
