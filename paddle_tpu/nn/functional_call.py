"""Functional bridge: run a stateful Layer as a pure function of its state.

This is the keystone that replaces the reference's eager autograd engine
(paddle/fluid/eager/ — egr::Backward, grad nodes): instead of taping grad
nodes per op, we rebind the module tree's parameters/buffers to (possibly
traced) values, run forward once under JAX's tracer, and let jax.grad /
jax.jit do AD and compilation.  Buffer mutations performed by layers during
forward (BatchNorm running stats, KV caches) are collected and returned, so
state updates stay functional under jit.

Usage (what train loops / hapi / fleet wrappers build on):

    params, buffers = state(model)
    def loss_fn(params, buffers, x, y, key):
        out, new_buf = functional_call(model, params, buffers, (x,), rng=key)
        return loss(out, y), new_buf
    (l, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(...)
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional, Tuple

import jax

from ..framework.random import rng_context
from .layer import Layer

__all__ = ["state", "parameters_dict", "buffers_dict", "functional_call",
           "bind_state", "TrainState"]


def parameters_dict(layer: Layer) -> Dict[str, jax.Array]:
    return dict(layer.named_parameters())


def buffers_dict(layer: Layer, persistable_only: bool = False) -> Dict[str, jax.Array]:
    return dict(layer.named_buffers(persistable_only=persistable_only))


def state(layer: Layer) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
    """Snapshot (params, buffers) as flat dotted-name pytrees."""
    return parameters_dict(layer), buffers_dict(layer)


def _index_stores(layer: Layer):
    """name -> (store_dict, key) for params and buffers separately."""
    pindex, bindex = {}, {}
    for lname, sub in layer.named_sublayers(include_self=True):
        for pname in sub._parameters:
            key = f"{lname}.{pname}" if lname else pname
            pindex[key] = (sub._parameters, pname)
        for bname in sub._buffers:
            key = f"{lname}.{bname}" if lname else bname
            bindex[key] = (sub._buffers, bname)
    return pindex, bindex


def _write(index, values: Dict[str, Any], strict: bool = True):
    for k, v in values.items():
        try:
            store, name = index[k]
        except KeyError:
            if strict:
                raise KeyError(f"no parameter/buffer named {k!r} in layer") from None
            continue
        store[name] = v


def _read(index) -> Dict[str, Any]:
    return {k: store[name] for k, (store, name) in index.items()}


@contextlib.contextmanager
def bind_state(layer: Layer, params: Optional[Dict[str, Any]] = None,
               buffers: Optional[Dict[str, Any]] = None):
    """Temporarily bind values into the module tree; restore originals on
    exit (so tracers never leak into the persistent module).  Yields a
    ``collect()`` closure returning the current (possibly updated) buffers."""
    pindex, bindex = _index_stores(layer)
    saved_p = _read(pindex)
    saved_b = _read(bindex)
    try:
        if params is not None:
            _write(pindex, params)
        if buffers is not None:
            _write(bindex, buffers)

        def collect() -> Dict[str, Any]:
            # re-index: forward may have registered new buffers (rare)
            _, bindex2 = _index_stores(layer)
            return {k: v for k, v in _read(bindex2).items() if v is not None}

        yield collect
    finally:
        _write(pindex, saved_p)
        # restore buffers to the pre-call snapshot; buffers registered
        # mid-trace are REMOVED (they'd otherwise hold leaked tracers)
        _, bindex3 = _index_stores(layer)
        for k, (store, name) in bindex3.items():
            if k in saved_b:
                store[name] = saved_b[k]
            else:
                del store[name]


def functional_call(layer: Layer, params: Dict[str, Any],
                    buffers: Optional[Dict[str, Any]], args: tuple = (),
                    kwargs: Optional[dict] = None, rng: Optional[jax.Array] = None,
                    train: Optional[bool] = None):
    """Pure-function call: returns (output, new_buffers)."""
    kwargs = kwargs or {}
    prev_modes = None
    if train is not None:
        prev_modes = [(l, l.training) for _, l in layer.named_sublayers(include_self=True)]
        (layer.train() if train else layer.eval())
    try:
        with bind_state(layer, params, buffers) as collect:
            if rng is not None:
                with rng_context(rng):
                    out = layer(*args, **kwargs)
            else:
                out = layer(*args, **kwargs)
            new_buffers = collect()
        return out, new_buffers
    finally:
        if prev_modes is not None:
            for l, mode in prev_modes:
                object.__setattr__(l, "training", mode)


class TrainState:
    """Mutable convenience holder for eager-style loops; the pytrees inside
    are what jitted steps consume/produce."""

    def __init__(self, layer: Layer):
        self.layer = layer
        self.params, self.buffers = state(layer)

    def sync_to_layer(self):
        pindex, bindex = _index_stores(self.layer)
        _write(pindex, self.params)
        _write(bindex, {k: v for k, v in self.buffers.items() if k in bindex},
               strict=False)
