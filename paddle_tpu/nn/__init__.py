"""paddle_tpu.nn — the layer zoo (parity surface: python/paddle/nn/)."""

from .layer import Layer, Parameter, ParamAttr  # noqa: F401
from .functional_call import (  # noqa: F401
    functional_call, state, parameters_dict, buffers_dict, bind_state,
    TrainState)
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from . import functional  # noqa: F401
from . import quant  # noqa: F401
from .layers import *  # noqa: F401,F403
from .layers import (  # noqa: F401
    container, common, conv, norm, pooling, activation, loss, transformer)

# gradient-clip classes at their reference location (python/paddle/nn/
# clip.py re-exports them; optimizer(grad_clip=...) is the use site)
from ..optimizer.clip import (  # noqa: F401,E402
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)
