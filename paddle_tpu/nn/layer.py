"""The Layer base class — a stateful module system over functional JAX.

Reference surface: python/paddle/nn/layer/layers.py — ``Layer`` (hooks,
``state_dict``/``set_state_dict``, ``create_parameter``, ``register_buffer``,
``sublayers``, ``train``/``eval``, ``to``) — SURVEY.md §2.2.

TPU-native design: a parameter is a plain ``jax.Array`` (no wrapper leaks to
user forward code). A Layer is a *container of names*:

  * ``self.weight = self.create_parameter(...)`` registers "weight" in
    ``_parameters`` and attribute access returns the raw array;
  * buffers (e.g. BatchNorm running stats) live in ``_buffers``; mutating
    them during a traced forward is captured by ``functional_call`` (see
    nn/functional_call.py) which snapshots/restores the tree around a trace
    and returns the updated buffer pytree — the eager mutation model the
    reference users expect, expressed functionally for XLA.

No autograd machinery lives here: gradients come from ``jax.grad`` over
``functional_call`` — the eager grad-node engine the reference builds
(paddle/fluid/eager/ — egr::Backward) is provided by JAX's trace-based AD.
"""

from __future__ import annotations

import copy
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import initializer as I

__all__ = ["Layer", "Parameter", "ParamAttr"]


class Parameter:
    """Assignment marker: ``self.w = Parameter(array)`` registers a trainable
    parameter. ``create_parameter`` returns one. Never stored — the raw array
    goes into ``_parameters``."""

    __slots__ = ("value", "trainable")

    def __init__(self, value, trainable: bool = True):
        self.value = jnp.asarray(value)
        self.trainable = trainable


class ParamAttr:
    """Parity shim for ``paddle.ParamAttr`` — carries name/initializer/
    regularizer/trainable/learning_rate hints into ``create_parameter``."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


class Layer:
    """Base class for all neural network layers."""

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_non_trainable", set())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistent_buffers", set())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_dtype", jnp.dtype(dtype) if dtype else jnp.float32)
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", OrderedDict())
        object.__setattr__(self, "_name_scope", name_scope or type(self).__name__)

    # ---- registration ---------------------------------------------------
    def create_parameter(self, shape, attr: Optional[ParamAttr] = None,
                         dtype=None, is_bias: bool = False,
                         default_initializer: Optional[I.Initializer] = None
                         ) -> Parameter:
        """Create + initialize a parameter (parity: Layer.create_parameter).

        Default init matches the reference's convention: XavierNormal for
        weights, zeros for biases (python/paddle/nn/initializer — the
        global default initializer).
        """
        dtype = jnp.dtype(dtype) if dtype is not None else self._dtype
        if attr is not None and attr.initializer is not None:
            # explicit ParamAttr wins over everything (reference contract)
            init = attr.initializer
        else:
            # set_global_initializer overrides layer defaults for params
            # created WITHOUT an explicit initializer (reference:
            # nn/initializer/__init__.py — set_global_initializer)
            init = I._global_initializer(is_bias) or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        value = init(tuple(shape), dtype=dtype)
        trainable = attr.trainable if attr is not None else True
        return Parameter(value, trainable=trainable)

    def add_parameter(self, name: str, parameter: Optional[Parameter]) -> None:
        if parameter is None:
            self._parameters[name] = None
            return
        if not isinstance(parameter, Parameter):
            parameter = Parameter(parameter)
        self._parameters[name] = parameter.value
        if not parameter.trainable:
            self._non_trainable.add(name)

    def register_buffer(self, name: str, tensor, persistable: bool = True) -> None:
        self._buffers[name] = None if tensor is None else jnp.asarray(tensor)
        if not persistable:
            self._non_persistent_buffers.add(name)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    # ---- attribute routing ----------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        if params is None:  # before Layer.__init__ ran
            object.__setattr__(self, name, value)
            return
        if isinstance(value, Parameter):
            self.__dict__.pop(name, None)
            self._sub_layers.pop(name, None)
            self._buffers.pop(name, None)
            self.add_parameter(name, value)
        elif isinstance(value, Layer):
            self.__dict__.pop(name, None)
            params.pop(name, None)
            self._buffers.pop(name, None)
            self._sub_layers[name] = value
        elif name in params:
            if value is None:
                params[name] = None
            else:
                params[name] = jnp.asarray(value) if not isinstance(value, jax.Array) else value
        elif name in self._buffers:
            self._buffers[name] = None if value is None else (
                value if isinstance(value, jax.Array) else jnp.asarray(value))
        elif name in self._sub_layers and isinstance(value, Layer):
            self._sub_layers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str) -> Any:
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name: str) -> None:
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---- traversal ------------------------------------------------------
    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self._sub_layers.items():
            if l is not None:
                yield l

    def named_children(self):
        return iter(self._sub_layers.items())

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, jax.Array]]:
        # traversal dedups shared (weight-tied) sublayers by id, matching
        # named_sublayers — a tied layer contributes its params once, under
        # its first path, so state_dict/functional_call indices agree
        if not include_sublayers:
            for name, p in self._parameters.items():
                if p is not None:
                    yield (f"{prefix}.{name}" if prefix else name), p
            return
        for lname, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, p in layer._parameters.items():
                if p is not None:
                    yield (f"{lname}.{name}" if lname else name), p

    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True,
                      persistable_only: bool = False):
        if not include_sublayers:
            for name, b in self._buffers.items():
                if b is None or (persistable_only and
                                 name in self._non_persistent_buffers):
                    continue
                yield (f"{prefix}.{name}" if prefix else name), b
            return
        for lname, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, b in layer._buffers.items():
                if b is None or (persistable_only and
                                 name in layer._non_persistent_buffers):
                    continue
                yield (f"{lname}.{name}" if lname else name), b

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # ---- state dict -----------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, jax.Array]:
        out = destination if destination is not None else OrderedDict()
        for k, v in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                          include_sublayers=include_sublayers):
            out[k] = v
        for k, v in self.named_buffers(prefix=structured_name_prefix.rstrip("."),
                                       include_sublayers=include_sublayers,
                                       persistable_only=True):
            out[k] = v
        return out

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        """Load a flat dotted-name dict. Returns (missing_keys, unexpected_keys)
        like the reference."""
        own = {}
        index: Dict[str, Tuple[Layer, str, str]] = {}
        for lname, layer in self.named_sublayers(include_self=True):
            for pname in layer._parameters:
                key = f"{lname}.{pname}" if lname else pname
                index[key] = (layer, "param", pname)
            for bname in layer._buffers:
                if bname in layer._non_persistent_buffers:
                    continue
                key = f"{lname}.{bname}" if lname else bname
                index[key] = (layer, "buffer", bname)
        missing = [k for k in index if k not in state_dict]
        unexpected = []
        for k, v in state_dict.items():
            if k not in index:
                unexpected.append(k)
                continue
            layer, kind, name = index[k]
            arr = jnp.asarray(v)
            cur = layer._parameters.get(name) if kind == "param" else layer._buffers.get(name)
            if cur is not None and tuple(cur.shape) != tuple(arr.shape):
                raise ValueError(
                    f"shape mismatch for {k}: got {arr.shape}, expected {cur.shape}")
            if cur is not None:
                arr = arr.astype(cur.dtype)
            if kind == "param":
                layer._parameters[name] = arr
            else:
                layer._buffers[name] = arr
        return missing, unexpected

    load_dict = set_state_dict

    # ---- mode / dtype ---------------------------------------------------
    def train(self) -> "Layer":
        for layer in self.named_sublayers(include_self=True):
            object.__setattr__(layer[1], "training", True)
        return self

    def eval(self) -> "Layer":
        for layer in self.named_sublayers(include_self=True):
            object.__setattr__(layer[1], "training", False)
        return self

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for sub in self.children():
            sub.apply(fn)
        fn(self)
        return self

    def to(self, device=None, dtype=None, blocking=None) -> "Layer":
        """Cast floating params/buffers (device moves are XLA's job)."""
        if dtype is not None:
            dtype = jnp.dtype(dtype)
            for _, layer in self.named_sublayers(include_self=True):
                for n, p in layer._parameters.items():
                    if p is not None and jnp.issubdtype(p.dtype, jnp.floating):
                        layer._parameters[n] = p.astype(dtype)
                for n, b in layer._buffers.items():
                    if b is not None and jnp.issubdtype(b.dtype, jnp.floating):
                        layer._buffers[n] = b.astype(dtype)
                object.__setattr__(layer, "_dtype", dtype)
        return self

    def astype(self, dtype) -> "Layer":
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype=jnp.float32)

    def bfloat16(self):
        return self.to(dtype=jnp.bfloat16)

    # ---- hooks ----------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # ---- call -----------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward()")

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    # ---- misc -----------------------------------------------------------
    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)


class _HookHandle:
    _next_id = 0

    def __init__(self, hooks_dict):
        self._hooks_dict = hooks_dict
        self.id = _HookHandle._next_id
        _HookHandle._next_id += 1

    def remove(self):
        self._hooks_dict.pop(self.id, None)
