"""Parameter initializers.

Reference: python/paddle/nn/initializer/ — Constant, Normal, TruncatedNormal,
Uniform, XavierNormal/XavierUniform, KaimingNormal/KaimingUniform, Assign
(SURVEY.md §2.2 "nn layers").

TPU-native: each initializer is a pure function of (key, shape, dtype); the
stateful eager path draws keys from the global generator
(paddle_tpu.framework.random).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.random import next_rng_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "calculate_gain", "Orthogonal", "Dirac"]


def calculate_gain(nonlinearity: str, param: Optional[float] = None) -> float:
    recipes = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity in recipes:
        return recipes[nonlinearity]
    raise ValueError(f"unsupported nonlinearity {nonlinearity!r}")


def _fan_in_out(shape: Sequence[int]) -> tuple[int, int]:
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    # Linear weights in this framework are [in, out] (paddle convention);
    # conv kernels are [out_c, in_c, *spatial] (paddle convention).
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def init(self, key: jax.Array, shape: Sequence[int], dtype) -> jax.Array:
        raise NotImplementedError

    def __call__(self, shape: Sequence[int], dtype="float32",
                 key: Optional[jax.Array] = None) -> jax.Array:
        if key is None:
            key = next_rng_key()
        return self.init(key, tuple(shape), jnp.dtype(dtype))


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def init(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def init(self, key, shape, dtype):
        return self.mean + self.std * jax.random.normal(key, shape, dtype=dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def init(self, key, shape, dtype):
        x = jax.random.truncated_normal(key, self.a, self.b, shape, dtype=dtype)
        return self.mean + self.std * x


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def init(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None,
                 gain: float = 1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def init(self, key, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype=dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None,
                 gain: float = 1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def init(self, key, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def init(self, key, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(key, shape, dtype=dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def init(self, key, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def init(self, key, shape, dtype):
        arr = jnp.asarray(self.value, dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(f"Assign shape {arr.shape} != requested {tuple(shape)}")
        return arr


class Orthogonal(Initializer):
    """Reference: paddle.nn.initializer.Orthogonal — (semi-)orthogonal
    matrix init via QR of a normal draw (rows/cols orthonormal depending
    on shape), scaled by ``gain``."""

    def __init__(self, gain: float = 1.0, name=None):
        self.gain = gain

    def init(self, key, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal requires >= 2 dims")
        rows = shape[0]
        cols = 1
        for s in shape[1:]:
            cols *= s
        flat = (rows, cols) if rows >= cols else (cols, rows)
        a = jax.random.normal(key, flat, dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        # sign correction for a unique decomposition
        q = q * jnp.sign(jnp.diagonal(r))[None, :]
        if rows < cols:
            q = q.T
        return (self.gain * q).reshape(shape).astype(dtype)


class Dirac(Initializer):
    """Reference: paddle.nn.initializer.Dirac — identity-preserving conv
    kernels ([out, in, *k] with a centered impulse per channel pair)."""

    def __init__(self, groups: int = 1, name=None):
        self.groups = groups

    def init(self, key, shape, dtype):
        if len(shape) < 3:
            raise ValueError("Dirac requires a conv kernel shape")
        out_c, in_c = shape[0], shape[1]
        w = jnp.zeros(shape, dtype)
        centers = tuple(s // 2 for s in shape[2:])
        per = out_c // self.groups
        # reference semantics: within each group, only the first
        # min(per, in_c) out-channels carry an impulse (channel-matched);
        # the rest stay zero — never duplicate input channels
        for g in range(self.groups):
            for d in range(min(per, in_c)):
                w = w.at[(g * per + d, d) + centers].set(1.0)
        return w


# --- global default initializer (reference: paddle.nn.initializer.
# set_global_initializer — the process-wide default create_parameter
# falls back to when neither attr nor the layer passes one) ---------------

_GLOBAL_INIT = [None, None]          # [weight_init, bias_init]


def set_global_initializer(weight_init, bias_init=None):
    """Reference: set_global_initializer(weight_init, bias_init); pass
    ``None, None`` to reset to the built-in defaults (XavierNormal /
    zeros)."""
    if weight_init is not None and not isinstance(weight_init, Initializer):
        raise TypeError("weight_init must be an Initializer or None")
    if bias_init is not None and not isinstance(bias_init, Initializer):
        raise TypeError("bias_init must be an Initializer or None")
    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


def _global_initializer(is_bias: bool):
    return _GLOBAL_INIT[1] if is_bias else _GLOBAL_INIT[0]


__all__ += ["set_global_initializer"]
