"""paddle.nn.utils parity — parameter vector round-trips, weight_norm /
spectral_norm reparameterizations, clip_grad_norm_ / clip_grad_value_.

Reference: python/paddle/nn/utils/ — transform_parameters.py
(parameters_to_vector / vector_to_parameters), weight_norm_hook.py,
spectral_norm_hook.py, clip_grad_norm_.py.

TPU-native notes: clipping is FUNCTIONAL (returns the clipped pytree — a
jit-safe value; the reference mutates .grad in place, which has no analog
here).  weight_norm/spectral_norm recompute the effective weight in a
forward pre-hook, exactly like the reference's hook mechanism.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .layer import Layer

__all__ = ["parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_", "weight_norm",
           "remove_weight_norm", "spectral_norm"]


def parameters_to_vector(parameters, name=None):
    """Flatten a parameter list (or dict values) into one 1-D vector."""
    if isinstance(parameters, dict):
        parameters = list(parameters.values())
    return jnp.concatenate([jnp.reshape(p, (-1,)) for p in parameters])


def vector_to_parameters(vec, parameters, name=None):
    """Split ``vec`` back into arrays shaped like ``parameters``; returns
    the new list (functional — the reference copies in place)."""
    if isinstance(parameters, dict):
        keys = list(parameters)
        vals = vector_to_parameters(vec, list(parameters.values()))
        return dict(zip(keys, vals))
    out: List = []
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        out.append(jnp.reshape(vec[offset:offset + n], p.shape)
                   .astype(p.dtype))
        offset += n
    return out


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0,
                    error_if_nonfinite: bool = False):
    """Returns (clipped_grads, total_norm).  Functional form of the
    reference's in-place grad clipping."""
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in leaves])) \
            ** (1.0 / norm_type)
    if error_if_nonfinite:
        import numpy as _np
        if not bool(_np.isfinite(jax.device_get(total))):
            raise RuntimeError("non-finite grad norm")
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree.map(lambda g: g * scale, grads), total


def clip_grad_value_(grads, clip_value: float):
    """Elementwise clamp to [-clip_value, clip_value] (functional)."""
    return jax.tree.map(lambda g: jnp.clip(g, -clip_value, clip_value),
                        grads)


def _norm_except(w, dim: int):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0) -> Layer:
    """Reparameterize ``layer.name`` as g * v/||v|| (reference
    weight_norm): v and g become the parameters; the effective weight is
    recomputed in a forward pre-hook."""
    w = layer._parameters[name]
    g0 = _norm_except(w, dim)
    del layer._parameters[name]
    layer._parameters[name + "_v"] = w
    layer._parameters[name + "_g"] = g0

    def pre_hook(lyr, inputs):
        v = lyr._parameters[name + "_v"]
        g = lyr._parameters[name + "_g"]
        n = _norm_except(v, dim)
        object.__setattr__(lyr, "_wn_cached", True)
        lyr._parameters[name] = g * v / jnp.maximum(n, 1e-12)
        return inputs

    handle = layer.register_forward_pre_hook(pre_hook)
    layer.__dict__["_weight_norm_handle"] = (handle, name, dim)
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight") -> Layer:
    """Fold the reparameterization back into a single weight."""
    handle, nm, dim = layer.__dict__.pop("_weight_norm_handle")
    handle.remove() if hasattr(handle, "remove") else None
    v = layer._parameters.pop(nm + "_v")
    g = layer._parameters.pop(nm + "_g")
    n = _norm_except(v, dim)
    layer._parameters[nm] = g * v / jnp.maximum(n, 1e-12)
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = 0) -> Layer:
    """Reference spectral_norm: weight / sigma_max, sigma estimated by
    power iteration on buffers u/v updated per forward."""
    w = layer._parameters[name]
    h = w.shape[dim]
    rest = int(np.prod(w.shape)) // h
    key = jax.random.PRNGKey(0)
    layer.register_buffer(name + "_u",
                          jax.random.normal(key, (h,)), persistable=True)
    layer.register_buffer(name + "_v",
                          jax.random.normal(jax.random.fold_in(key, 1),
                                            (rest,)), persistable=True)
    del layer._parameters[name]
    layer._parameters[name + "_orig"] = w

    def pre_hook(lyr, inputs):
        w0 = lyr._parameters[name + "_orig"]
        wm = jnp.moveaxis(w0, dim, 0).reshape(h, rest)
        u = lyr._buffers[name + "_u"]
        v = lyr._buffers[name + "_v"]
        for _ in range(n_power_iterations):
            v = wm.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = wm @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ wm @ v
        lyr._buffers[name + "_u"] = jax.lax.stop_gradient(u)
        lyr._buffers[name + "_v"] = jax.lax.stop_gradient(v)
        lyr._parameters[name] = w0 / jnp.maximum(sigma, eps)
        return inputs

    layer.register_forward_pre_hook(pre_hook)
    return layer
