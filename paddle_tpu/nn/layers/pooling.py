"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""

from __future__ import annotations

from .. import functional as F
from ..layer import Layer

__all__ = ["MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D", "AvgPool2D",
           "AvgPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 data_format=None, **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format
        self.kw = kw


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode, data_format=self.data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         exclusive=exclusive)

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            exclusive=self.kw["exclusive"],
                            ceil_mode=self.ceil_mode, data_format=self.data_format)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         exclusive=exclusive, divisor_override=divisor_override)

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.kw["exclusive"],
                            self.kw["divisor_override"], self.data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format,
                         exclusive=exclusive, divisor_override=divisor_override)

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.kw["exclusive"],
                            self.kw["divisor_override"], self.data_format)


class _AdaptivePool(Layer):
    def __init__(self, output_size, data_format=None, **kw):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(output_size, data_format)

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(_AdaptivePool):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size, data_format)

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePool):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size)

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

class _MaxUnPoolND(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride = kernel_size, stride
        self.padding, self.output_size = padding, output_size
        self.data_format = data_format

    def forward(self, x, indices):
        return type(self)._fn(x, indices, self.kernel_size,
                              stride=self.stride, padding=self.padding,
                              data_format=self.data_format,
                              output_size=self.output_size)


class MaxUnPool1D(_MaxUnPoolND):
    _fn = staticmethod(F.max_unpool1d)

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)


class MaxUnPool2D(_MaxUnPoolND):
    _fn = staticmethod(F.max_unpool2d)

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)


class MaxUnPool3D(_MaxUnPoolND):
    _fn = staticmethod(F.max_unpool3d)

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__(kernel_size, stride, padding, data_format,
                         output_size, name)


__all__ += ["MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D"]


class LPPool1D(Layer):
    """Reference: paddle.nn.LPPool1D — power-average pooling."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class LPPool2D(Layer):
    """Reference: paddle.nn.LPPool2D."""

    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class FractionalMaxPool2D(Layer):
    """Reference: paddle.nn.FractionalMaxPool2D."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(
            x, self.output_size, self.kernel_size, self.random_u,
            self.return_mask)


class FractionalMaxPool3D(Layer):
    """Reference: paddle.nn.FractionalMaxPool3D."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(
            x, self.output_size, self.kernel_size, self.random_u,
            self.return_mask)


__all__ += ["LPPool1D", "LPPool2D", "FractionalMaxPool2D",
            "FractionalMaxPool3D"]
