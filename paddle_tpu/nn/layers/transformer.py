"""Transformer layers (BASELINE config #2).

Reference: python/paddle/nn/layer/transformer.py — MultiHeadAttention (with
Cache/StaticCache incremental decoding), TransformerEncoderLayer,
TransformerEncoder, TransformerDecoderLayer, TransformerDecoder, Transformer.

TPU-native: attention math goes through F.scaled_dot_product_attention which
routes to the Pallas flash kernel when profitable; otherwise plain XLA einsum
(MXU-friendly, fp32 softmax accumulation).
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp

from .. import functional as F
from ..layer import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
           "TransformerDecoderLayer", "TransformerDecoder", "Transformer"]


def _convert_attention_mask(attn_mask, dtype):
    """Bool mask (True=keep) -> additive; numeric passes through (parity:
    reference _convert_attention_mask)."""
    if attn_mask is None:
        return None
    if attn_mask.dtype == jnp.bool_:
        return jnp.where(attn_mask, 0.0, jnp.finfo(jnp.float32).min).astype(jnp.float32)
    return attn_mask.astype(jnp.float32)


class MultiHeadAttention(Layer):
    """Inputs [batch, seq, embed_dim]; heads split internally (paddle layout
    [B, S, H, D] for the attention core)."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout: float = 0.0,
                 kdim=None, vdim=None, need_weights: bool = False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim, \
            "embed_dim must be divisible by num_heads"
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.num_heads, self.head_dim)

    def gen_cache(self, key, value=None, type=None):
        """Parity with reference gen_cache: returns StaticCache (cross-attn,
        precomputed k/v) or Cache (incremental self-attn)."""
        if type == MultiHeadAttention.StaticCache or (value is not None and type is None):
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        # empty rolling cache; key arg carries batch size reference input
        b = key.shape[0]
        k = jnp.zeros((b, 0, self.num_heads, self.head_dim), key.dtype)
        return self.Cache(k, jnp.zeros_like(k))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
            new_cache = cache
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                k = jnp.concatenate([cache.k, k], axis=1)
                v = jnp.concatenate([cache.v, v], axis=1)
                new_cache = self.Cache(k, v)
            else:
                new_cache = None
        mask = _convert_attention_mask(attn_mask, q.dtype)
        if mask is not None and mask.ndim == 3:
            mask = mask[:, None]  # [B,1,Sq,Sk] broadcast over heads
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        b, s = out.shape[:2]
        out = self.out_proj(out.reshape(b, s, self.embed_dim))
        if cache is not None:
            return out, new_cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout: float = 0.1,
                 activation: str = "relu", attn_dropout=None, act_dropout=None,
                 normalize_before: bool = False, weight_attr=None,
                 bias_attr=None, layer_norm_eps: float = 1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self._config = dict(d_model=d_model, nhead=nhead,
                            dim_feedforward=dim_feedforward, dropout=dropout,
                            activation=activation, attn_dropout=attn_dropout,
                            act_dropout=act_dropout,
                            normalize_before=normalize_before,
                            weight_attr=weight_attr, bias_attr=bias_attr,
                            layer_norm_eps=layer_norm_eps)
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


def _clone_layer(layer):
    """Fresh-init clone, matching the reference's per-depth construction
    (python/paddle/nn/layer/transformer.py rebuilds from the layer's config
    rather than deepcopying weights — identical init across depth measurably
    hurts early training)."""
    cfg = getattr(layer, "_config", None)
    if cfg is not None:
        return type(layer)(**cfg)
    import copy
    return copy.deepcopy(layer)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers: int, norm=None):
        super().__init__()
        self.layers = LayerList([encoder_layer] + [
            _clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_c = mod(output, src_mask, cache[i])
                new_caches.append(new_c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout: float = 0.1,
                 activation: str = "relu", attn_dropout=None, act_dropout=None,
                 normalize_before: bool = False, weight_attr=None,
                 bias_attr=None, layer_norm_eps: float = 1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self._config = dict(d_model=d_model, nhead=nhead,
                            dim_feedforward=dim_feedforward, dropout=dropout,
                            activation=activation, attn_dropout=attn_dropout,
                            act_dropout=act_dropout,
                            normalize_before=normalize_before,
                            weight_attr=weight_attr, bias_attr=bias_attr,
                            layer_norm_eps=layer_norm_eps)
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache, static_cache = None, None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (incremental_cache, static_cache)

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory,
                                               type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers: int, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            _clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_c = mod(output, memory, tgt_mask, memory_mask,
                                    cache[i])
                new_caches.append(new_c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """Full encoder-decoder (parity: paddle.nn.Transformer)."""

    def __init__(self, d_model: int = 512, nhead: int = 8,
                 num_encoder_layers: int = 6, num_decoder_layers: int = 6,
                 dim_feedforward: int = 2048, dropout: float = 0.1,
                 activation: str = "relu", attn_dropout=None, act_dropout=None,
                 normalize_before: bool = False, weight_attr=None,
                 bias_attr=None, custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length: int):
        i = jnp.arange(length)[:, None]
        j = jnp.arange(length)[None, :]
        return jnp.where(j <= i, 0.0, jnp.finfo(jnp.float32).min)
