"""Recurrent layers — SimpleRNN/LSTM/GRU cells and runners.

Reference: python/paddle/nn/layer/rnn.py — SimpleRNNCell/LSTMCell/GRUCell,
RNN/BiRNN runners, SimpleRNN/LSTM/GRU stacks (backed by cudnn kernels on
GPU; SURVEY.md §2.2 nn layers row).

TPU-native: one ``lax.scan`` over time per direction — the step body is a
dense cell whose matmuls hit the MXU; XLA fuses gate elementwise ops into
them.  Parameter names/layouts match the reference (weight_ih
[gates*H, I], weight_hh [gates*H, H], bias_ih/bias_hh [gates*H]; LSTM gate
order i,f,g,o; GRU gate order r,z,c) so state_dicts port.
``sequence_length`` freezes states and zeroes outputs past each sequence's
length, like the reference's variable-length handling.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..layer import Layer
from .. import initializer as I

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


class _RNNCellBase(Layer):
    def __init__(self, input_size: int, hidden_size: int, gates: int,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        g = gates * hidden_size
        self.weight_ih = self.create_parameter((g, input_size),
                                               attr=weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter((g, hidden_size),
                                               attr=weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter((g,), attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter((g,), attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    def get_initial_states(self, batch):
        raise NotImplementedError


class SimpleRNNCell(_RNNCellBase):
    """h' = act(W_ih x + b_ih + W_hh h + b_hh); activation tanh|relu."""

    def __init__(self, input_size, hidden_size, activation: str = "tanh",
                 **kw):
        super().__init__(input_size, hidden_size, gates=1, **kw)
        if activation not in ("tanh", "relu"):
            raise ValueError(f"bad activation {activation!r}")
        self.activation = activation

    def forward(self, x, state=None):
        h = self.get_initial_states(x.shape[0]) if state is None else state
        z = x @ self.weight_ih.T + self.bias_ih + \
            h @ self.weight_hh.T + self.bias_hh
        h2 = jnp.tanh(z) if self.activation == "tanh" else jnp.maximum(z, 0)
        return h2, h2

    def get_initial_states(self, batch):
        return jnp.zeros((batch, self.hidden_size),
                         self.weight_ih.dtype)


class LSTMCell(_RNNCellBase):
    """Gate order i, f, g(cell), o (reference layout)."""

    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, gates=4, **kw)

    def forward(self, x, state=None):
        h, c = self.get_initial_states(x.shape[0]) if state is None \
            else state
        z = x @ self.weight_ih.T + self.bias_ih + \
            h @ self.weight_hh.T + self.bias_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return h2, (h2, c2)

    def get_initial_states(self, batch):
        z = jnp.zeros((batch, self.hidden_size), self.weight_ih.dtype)
        return (z, z)


class GRUCell(_RNNCellBase):
    """Gate order r, z, c; candidate uses r * (W_hc h + b_hc) (reference
    convention)."""

    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, gates=3, **kw)

    def forward(self, x, state=None):
        h = self.get_initial_states(x.shape[0]) if state is None else state
        gi = x @ self.weight_ih.T + self.bias_ih
        gh = h @ self.weight_hh.T + self.bias_hh
        ir, iz, ic = jnp.split(gi, 3, axis=-1)
        hr, hz, hc = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        c = jnp.tanh(ic + r * hc)
        h2 = (1.0 - z) * c + z * h
        return h2, h2

    def get_initial_states(self, batch):
        return jnp.zeros((batch, self.hidden_size),
                         self.weight_ih.dtype)


def _scan_cell(cell, inputs, init_state, seq_lens=None, reverse=False):
    """inputs [B, T, I] -> (outputs [B, T, H], final_state).  States past
    ``seq_lens`` freeze; their outputs zero (reference varlen handling).
    """
    T = inputs.shape[1]
    xs = jnp.moveaxis(inputs, 1, 0)                     # [T, B, I]

    def body(state, tx):
        t, x_t = tx
        out, new_state = cell(x_t, state)
        if seq_lens is not None:
            valid = (t < seq_lens)[:, None]
            out = jnp.where(valid, out, jnp.zeros_like(out))
            new_state = jax.tree.map(
                lambda n, s: jnp.where(valid, n, s), new_state, state)
        return new_state, out

    # lax.scan threads xs per step natively; reverse=True walks t=T-1..0
    # and still stacks outputs in ORIGINAL time order — no index gather,
    # no post-hoc flip
    final, outs = jax.lax.scan(body, init_state, (jnp.arange(T), xs),
                               reverse=reverse)
    return jnp.moveaxis(outs, 0, 1), final              # [B, T, H]


class RNN(Layer):
    """Runner: scans ``cell`` over the time dim (reference: nn.RNN)."""

    def __init__(self, cell, is_reverse: bool = False,
                 time_major: bool = False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if self.time_major:
            inputs = jnp.moveaxis(inputs, 0, 1)
        init = self.cell.get_initial_states(inputs.shape[0]) \
            if initial_states is None else initial_states
        outs, final = _scan_cell(self.cell, inputs, init,
                                 seq_lens=sequence_length,
                                 reverse=self.is_reverse)
        if self.time_major:
            outs = jnp.moveaxis(outs, 0, 1)
        return outs, final


class BiRNN(Layer):
    """Two runners, outputs concatenated (reference: nn.BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major: bool = False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if self.time_major:
            inputs = jnp.moveaxis(inputs, 0, 1)
        init_fw, init_bw = (initial_states if initial_states is not None
                            else (self.cell_fw.get_initial_states(
                                      inputs.shape[0]),
                                  self.cell_bw.get_initial_states(
                                      inputs.shape[0])))
        out_f, fin_f = _scan_cell(self.cell_fw, inputs, init_fw,
                                  seq_lens=sequence_length, reverse=False)
        out_b, fin_b = _scan_cell(self.cell_bw, inputs, init_bw,
                                  seq_lens=sequence_length, reverse=True)
        outs = jnp.concatenate([out_f, out_b], axis=-1)
        if self.time_major:
            outs = jnp.moveaxis(outs, 0, 1)
        return outs, (fin_f, fin_b)


class _RNNStack(Layer):
    """Multi-layer (optionally bidirectional) stack shared by
    SimpleRNN/LSTM/GRU (reference behavior incl. inter-layer dropout)."""

    CELL = None
    _cell_kwargs: dict = {}

    def __init__(self, input_size, hidden_size, num_layers: int = 1,
                 direction: str = "forward", time_major: bool = False,
                 dropout: float = 0.0, **cell_kw):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"bad direction {direction!r}")
        self.bidirect = direction != "forward"
        self.time_major = time_major
        self.dropout = dropout
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        mult = 2 if self.bidirect else 1
        layers = []
        for li in range(num_layers):
            in_sz = input_size if li == 0 else hidden_size * mult
            if self.bidirect:
                layers.append(BiRNN(self.CELL(in_sz, hidden_size, **cell_kw),
                                    self.CELL(in_sz, hidden_size, **cell_kw)))
            else:
                layers.append(RNN(self.CELL(in_sz, hidden_size, **cell_kw)))
        from .container import LayerList
        self.layers = LayerList(layers)

    @property
    def _is_lstm(self):
        return self.CELL is LSTMCell

    def _split_initial(self, initial_states, li):
        """Reference contract: stacked [L*D, B, H] tensors (a (h, c) pair
        of them for LSTM) -> this layer's per-direction cell states."""
        if initial_states is None:
            return None
        D = 2 if self.bidirect else 1

        def pick(s, idx):
            return s[idx]

        if self._is_lstm:
            h, c = initial_states
            if self.bidirect:
                return ((pick(h, D * li), pick(c, D * li)),
                        (pick(h, D * li + 1), pick(c, D * li + 1)))
            return (pick(h, li), pick(c, li))
        h = initial_states
        if self.bidirect:
            return (pick(h, D * li), pick(h, D * li + 1))
        return pick(h, li)

    def _stack_finals(self, finals):
        """Per-layer finals -> reference stacked [L*D, B, H] (pair for
        LSTM)."""
        hs, cs = [], []
        for fin in finals:
            per_dir = fin if self.bidirect else (fin,)
            for f in per_dir:
                if self._is_lstm:
                    hs.append(f[0])
                    cs.append(f[1])
                else:
                    hs.append(f)
        h = jnp.stack(hs, axis=0)
        if self._is_lstm:
            return (h, jnp.stack(cs, axis=0))
        return h

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = jnp.moveaxis(inputs, 0, 1) if self.time_major else inputs
        finals = []
        for li, layer in enumerate(self.layers):
            x, fin = layer(x, self._split_initial(initial_states, li),
                           sequence_length=sequence_length)
            finals.append(fin)
            if self.dropout and li < self.num_layers - 1 and self.training:
                from ..functional.common import dropout as _dropout
                x = _dropout(x, p=self.dropout, training=True)
        if self.time_major:
            x = jnp.moveaxis(x, 0, 1)
        return x, self._stack_finals(finals)


class SimpleRNN(_RNNStack):
    CELL = SimpleRNNCell


class LSTM(_RNNStack):
    CELL = LSTMCell


class GRU(_RNNStack):
    CELL = GRUCell


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

RNNCellBase = _RNNCellBase  # public alias (reference: nn.RNNCellBase)


class BeamSearchDecoder(Layer):
    """Beam-search decoding over an RNN cell (reference:
    nn.BeamSearchDecoder + dynamic_decode, seq2seq text generation).

    TPU-native: the whole decode is one ``lax.scan`` over time with a
    static ``beam_size`` — beams live on a leading [B*K] batch axis,
    length-penalty-free log-prob accumulation, finished beams propagate
    END tokens.  ``decode(init_cell_states, max_steps)`` returns
    (token ids [B, K, T], scores [B, K]) sorted best-first.
    """

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def decode(self, init_states, max_steps: int):
        import jax
        K = self.beam_size

        def expand(t):
            return jnp.repeat(t, K, axis=0)  # [B,...] -> [B*K,...]

        states = jax.tree.map(expand, init_states)
        leaf0 = jax.tree_util.tree_leaves(init_states)[0]
        B = leaf0.shape[0]
        neg_inf = jnp.asarray(-1e9, jnp.float32)
        # only beam 0 of each batch row is live at t=0 (others -inf so the
        # first top-k doesn't pick duplicate roots)
        scores = jnp.tile(jnp.asarray([0.0] + [-1e9] * (K - 1),
                                      jnp.float32), (B,))     # [B*K]
        tokens0 = jnp.full((B * K,), self.start_token, jnp.int32)
        finished0 = jnp.zeros((B * K,), bool)

        def step(carry, _):
            tokens, scores, finished, states = carry
            inp = self.embedding_fn(tokens) if self.embedding_fn \
                else jax.nn.one_hot(tokens, self.cell.input_size)
            out, new_states = self.cell(inp, states)
            logits = self.output_fn(out) if self.output_fn else out
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1)     # [B*K, V]
            V = logp.shape[-1]
            # finished beams only extend with END at zero cost
            end_only = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
            logp = jnp.where(finished[:, None], end_only[None, :], logp)
            total = scores[:, None] + logp               # [B*K, V]
            flat = total.reshape(B, K * V)
            top_scores, top_idx = jax.lax.top_k(flat, K)  # [B, K]
            beam_idx = top_idx // V                       # source beam
            tok = (top_idx % V).astype(jnp.int32)
            src = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
            new_states = jax.tree.map(lambda s: s[src], new_states)
            new_tokens = tok.reshape(-1)
            new_scores = top_scores.reshape(-1)
            new_finished = finished[src] | (new_tokens == self.end_token)
            return ((new_tokens, new_scores, new_finished, new_states),
                    (new_tokens, src))

        (tokens, scores, finished, _), (toks, srcs) = jax.lax.scan(
            step, (tokens0, scores, finished0, states), None,
            length=max_steps)
        # backtrace: follow src pointers from the last step
        T = max_steps

        def back(carry, t_rev):
            ptr = carry                                  # [B*K]
            tok = toks[t_rev][ptr]
            ptr = srcs[t_rev][ptr]
            return ptr, tok

        ptr0 = jnp.arange(B * self.beam_size)
        _, rev = jax.lax.scan(back, ptr0, jnp.arange(T - 1, -1, -1))
        seq = jnp.flip(rev, axis=0).T                    # [B*K, T]
        return (seq.reshape(B, self.beam_size, T),
                scores.reshape(B, self.beam_size))


__all__ += ["RNNCellBase", "BeamSearchDecoder"]


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Reference: paddle.nn.dynamic_decode — drive a Decoder to
    completion.  Here the whole decode is already ONE compiled lax.scan
    inside BeamSearchDecoder.decode, so this is the thin entry point:
    returns (ids, scores) ([B, K, T] best-first, [B, K]); the reference's
    (outputs, final_states[, sequence_lengths]) shape bookkeeping is
    subsumed by the static-shape scan (documented deviation).  Length
    accounting (return_length) counts tokens before the first end token.

    ``max_step_num`` is REQUIRED (documented deviation): the reference's
    decode-until-all-finished loop is data-dependent; the compiled scan
    needs a static bound — silently picking one would truncate outputs."""
    if max_step_num is None:
        raise ValueError(
            "dynamic_decode requires max_step_num: the compiled decode "
            "scan needs a static step bound (the reference's "
            "until-finished loop is data-dependent)")
    if inits is None:
        raise ValueError(
            "dynamic_decode requires inits (the decoder cell's initial "
            "states); the reference's decoder.initialize() fallback needs "
            "a batch size this static-shape API cannot infer")
    steps = int(max_step_num)
    ids, scores = decoder.decode(inits, steps)
    if return_length:
        end = getattr(decoder, "end_token", None)
        if end is None:
            lengths = jnp.full(ids.shape[:2], ids.shape[-1], jnp.int64)
        else:
            hit = jnp.cumsum((ids == end).astype(jnp.int32), axis=-1) > 0
            lengths = jnp.sum(~hit, axis=-1).astype(jnp.int64)
        return ids, scores, lengths
    return ids, scores


__all__ += ["dynamic_decode"]
