"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from ..layer import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Sigmoid", "Tanh",
           "Softmax", "LogSoftmax", "LeakyReLU", "ELU", "SELU", "CELU",
           "Hardswish", "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink",
           "Tanhshrink", "Softplus", "Softsign", "Mish", "GLU", "PReLU",
           "RReLU", "Maxout", "ThresholdedReLU", "LogSigmoid"]


def _simple(name, fn_name, **defaults):
    def __init__(self, name=None, **kw):
        Layer.__init__(self)
        self._kw = {**defaults, **kw}

    def forward(self, x):
        return getattr(F, fn_name)(x, **self._kw)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
SiLU = _simple("SiLU", "silu")
Swish = _simple("Swish", "swish")
Sigmoid = _simple("Sigmoid", "sigmoid")
Tanh = _simple("Tanh", "tanh")
Hardswish = _simple("Hardswish", "hardswish")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
Softsign = _simple("Softsign", "softsign")
Mish = _simple("Mish", "mish")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")


class GELU(Layer):
    def __init__(self, approximate: bool = False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self.approximate)


class Softmax(Layer):
    def __init__(self, axis: int = -1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis: int = -1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope: float = 0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha: float = 1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha: float = 1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold: float = 0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold: float = 0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Softplus(Layer):
    def __init__(self, beta: float = 1.0, threshold: float = 20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class GLU(Layer):
    def __init__(self, axis: int = -1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters: int = 1, init: float = 0.25,
                 weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Maxout(Layer):
    def __init__(self, groups: int, axis: int = 1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold: float = 1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input (reference:
    nn.Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError("Softmax2D expects 3-D or 4-D input")
        return F.softmax(x, axis=-3)


class GumbelSoftmax(Layer):
    def __init__(self, temperature=1.0, hard=False, axis=-1, name=None):
        super().__init__()
        self.temperature, self.hard, self.axis = temperature, hard, axis

    def forward(self, x):
        return F.gumbel_softmax(x, temperature=self.temperature,
                                hard=self.hard, axis=self.axis)


__all__ += ["Softmax2D", "GumbelSoftmax"]
