"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""

from __future__ import annotations

from .. import functional as F
from ..layer import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "SmoothL1Loss", "KLDivLoss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "CosineEmbeddingLoss",
           "CTCLoss", "TripletMarginLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index: int = -100,
                 reduction: str = "mean", soft_label: bool = False,
                 axis: int = -1, use_softmax: bool = True,
                 label_smoothing: float = 0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction: str = "mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction: str = "mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index: int = -100,
                 reduction: str = "mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction: str = "mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction: str = "mean", delta: float = 1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction: str = "mean", log_target: bool = False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin: float = 0.0, reduction: str = "mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin: float = 1.0, reduction: str = "mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin: float = 0.0, reduction: str = "mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank: int = 0, reduction: str = "mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class TripletMarginLoss(Layer):
    def __init__(self, margin: float = 1.0, p: float = 2.0, epsilon: float = 1e-6,
                 swap: bool = False, reduction: str = "mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap, self.reduction = swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(
            input, label, weight=self.weight, reduction=self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon = full, epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, log_input=self.log_input,
                                  full=self.full, epsilon=self.epsilon,
                                  reduction=self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p,
                                   margin=self.margin, weight=self.weight,
                                   reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, margin=self.margin,
            swap=self.swap, reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference: nn.HSigmoidLoss): owns the
    [num_classes-1, feature_size] internal-node weight table."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        from .. import initializer as I
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (num_classes - 1,), attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=self.bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Clustered softmax head (reference: nn.AdaptiveLogSoftmaxWithLoss;
    Grave et al.).  ``cutoffs`` EXCLUDES n_classes (reference signature);
    tail cluster i projects to dim in_features / div_value**(i+1)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        from .. import initializer as I
        self.cutoffs = list(cutoffs) + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        shortlist = self.cutoffs[0]
        head_size = shortlist + self.n_clusters
        self.head_weight = self.create_parameter(
            (in_features, head_size), default_initializer=I.XavierUniform())
        self.head_bias = None
        if head_bias:
            self.head_bias = self.create_parameter(
                (head_size,), is_bias=True)
        self._tails = []
        for i in range(self.n_clusters):
            d = max(1, int(in_features / (div_value ** (i + 1))))
            size = self.cutoffs[i + 1] - self.cutoffs[i]
            setattr(self, f"tail_{i}_proj", self.create_parameter(
                (in_features, d), default_initializer=I.XavierUniform()))
            setattr(self, f"tail_{i}_emb", self.create_parameter(
                (d, size), default_initializer=I.XavierUniform()))
            self._tails.append((f"tail_{i}_proj", f"tail_{i}_emb"))

    def forward(self, input, label):
        tails = [(self._parameters[p], self._parameters[e])
                 for p, e in self._tails]
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, tails, self.cutoffs,
            head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities."""
        import jax
        import jax.numpy as jnp
        x = jnp.asarray(input)
        head_logits = jnp.matmul(x, jnp.asarray(self.head_weight))
        if self.head_bias is not None:
            head_logits = head_logits + jnp.asarray(self.head_bias)
        head_logp = jax.nn.log_softmax(head_logits, axis=-1)
        shortlist = self.cutoffs[0]
        parts = [head_logp[:, :shortlist]]
        for i in range(self.n_clusters):
            pw = jnp.asarray(self._parameters[f"tail_{i}_proj"])
            ew = jnp.asarray(self._parameters[f"tail_{i}_emb"])
            tail_logp = jax.nn.log_softmax(
                jnp.matmul(jnp.matmul(x, pw), ew), axis=-1)
            parts.append(head_logp[:, shortlist + i:shortlist + i + 1]
                         + tail_logp)
        return jnp.concatenate(parts, axis=-1)

    def predict(self, input):
        import jax.numpy as jnp
        return jnp.argmax(self.log_prob(input), axis=-1)


__all__ += ["SoftMarginLoss", "MultiLabelSoftMarginLoss", "GaussianNLLLoss",
            "PoissonNLLLoss", "MultiMarginLoss",
            "TripletMarginWithDistanceLoss", "HSigmoidLoss",
            "AdaptiveLogSoftmaxWithLoss"]


class RNNTLoss(Layer):
    """RNN-Transducer loss layer (reference: paddle.nn.RNNTLoss over the
    warprnnt kernel; see functional.rnnt_loss for the DP + FastEmit
    contract)."""

    def __init__(self, blank: int = 0, fastemit_lambda: float = 0.001,
                 reduction: str = "mean", name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


__all__ += ["RNNTLoss"]
