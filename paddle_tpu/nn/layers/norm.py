"""Normalization layers.

Reference: python/paddle/nn/layer/norm.py — BatchNorm1D/2D/3D, LayerNorm,
GroupNorm, InstanceNorm*, SyncBatchNorm, SpectralNorm; RMSNorm from
paddle.incubate.nn.  Running stats are registered buffers so
functional_call captures training-time updates (SURVEY.md §7.1).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import functional as F
from .. import initializer as I
from ..layer import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "RMSNorm", "LocalResponseNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        out = F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                           training=self.training, momentum=self.momentum,
                           epsilon=self.epsilon, data_format=self.data_format,
                           use_global_stats=self.use_global_stats)
        if isinstance(out, tuple):
            y, new_rm, new_rv = out
            self._mean = new_rm
            self._variance = new_rv
            return y
        return out

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else "NHWC")


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/GSPMD the batch axis is sharded and XLA
    computes global-batch statistics automatically when reductions span the
    sharded axis — so forward is identical to BatchNorm; kept as a distinct
    class for API parity (reference: python/paddle/nn/layer/norm.py —
    SyncBatchNorm, backed by sync_batch_norm CUDA+NCCL kernel).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer: Layer) -> Layer:
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer.num_features, layer.momentum, layer.epsilon,
                                data_format=layer.data_format)
            new.set_state_dict(dict(layer.state_dict()))
            return new
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter(self.normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """Llama-family RMS norm (reference: paddle.incubate.nn.FusedRMSNorm /
    rms_norm — fused CUDA; here a 3-op XLA expression fused automatically)."""

    def __init__(self, normalized_shape, epsilon: float = 1e-6,
                 weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, None, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.add_parameter("weight", None)
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((num_channels,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.add_parameter("weight", None)
            self.add_parameter("bias", None)
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

class SpectralNorm(Layer):
    """Spectral normalization of a WEIGHT tensor (reference:
    nn.SpectralNorm — forward(weight) returns weight / sigma, with the
    power-iteration vectors carried as buffers)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        import numpy as _np
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = int(weight_shape[dim])
        w = int(_np.prod(weight_shape)) // h
        rs = _np.random.RandomState(0)
        self.register_buffer("weight_u", jnp.asarray(
            rs.randn(h).astype(_np.float32)))
        self.register_buffer("weight_v", jnp.asarray(
            rs.randn(w).astype(_np.float32)))

    def forward(self, weight):
        w = jnp.moveaxis(jnp.asarray(weight), self.dim, 0)
        mat = w.reshape(w.shape[0], -1)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        self.weight_u = u
        self.weight_v = v
        sigma = u @ mat @ v
        out = mat / sigma
        return jnp.moveaxis(out.reshape(w.shape), 0, self.dim)


__all__ += ["SpectralNorm"]
