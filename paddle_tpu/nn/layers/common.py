"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample.

Reference: python/paddle/nn/layer/common.py — Linear, Embedding, Dropout,
Flatten, Upsample, Pad2D, ...
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import functional as F
from .. import initializer as I
from ..layer import Layer, Parameter, ParamAttr

__all__ = ["Linear", "Embedding", "Dropout", "Dropout2D", "Dropout3D",
           "AlphaDropout", "FeatureAlphaDropout",
           "Flatten", "Identity", "Pad1D", "Pad2D", "Pad3D",
           "ZeroPad2D", "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
           "Bilinear", "CosineSimilarity", "Unfold", "Fold", "PixelShuffle",
           "PixelUnshuffle", "ChannelShuffle"]


class Linear(Layer):
    """y = xW + b, W: [in_features, out_features] (paddle layout)."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if (weight_attr and weight_attr.initializer)
            else I.XavierNormal())
        if bias_attr is False:
            self.bias = None
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings: int, embedding_dim: int,
                 padding_idx: Optional[int] = None, sparse: bool = False,
                 weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=None if (weight_attr and weight_attr.initializer)
            else I.Normal(0.0, 1.0))
        if padding_idx is not None:
            w = self._parameters["weight"]
            self._parameters["weight"] = w.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p: float = 0.5, axis=None, mode: str = "upscale_in_train",
                 name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p: float = 0.5, data_format: str = "NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p: float = 0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class FeatureAlphaDropout(Layer):
    """Reference: paddle.nn.FeatureAlphaDropout — alpha dropout over whole
    channels."""

    def __init__(self, p: float = 0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis: int = 1, stop_axis: int = -1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        start = self.start_axis % x.ndim
        stop = self.stop_axis % x.ndim
        new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
        return x.reshape(new_shape)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class _PadND(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad2D(_PadND):
    pass


class Pad3D(_PadND):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class ZeroPad2D(_PadND):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        if bias_attr is False:
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((out_features,), is_bias=True,
                                              attr=bias_attr)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis: int = 1, eps: float = 1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor: int, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor: int, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups: int, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


# --- round-3 op-coverage additions (OP_COVERAGE.md) ----------------------

class CircularPad2D(_PadND):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "circular", 0.0, data_format, name)


class CircularPad3D(_PadND):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "circular", 0.0, data_format, name)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, p=self.p, epsilon=self.epsilon,
                                   keepdim=self.keepdim)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ...tensor.manipulation import unflatten as _unflatten
        return _unflatten(x, self.axis, self.shape)


__all__ += ["CircularPad2D", "CircularPad3D", "PairwiseDistance",
            "Unflatten"]
