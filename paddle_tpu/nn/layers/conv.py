"""Conv layers (reference: python/paddle/nn/layer/conv.py — Conv1D..Conv3DTranspose).

Weight layout matches paddle: [out_c, in_c/groups, *k] for conv,
[in_c, out_c/groups, *k] for transpose.  Default init KaimingUniform-style
(paddle uses Normal(0, sqrt(2/fan_in))-ish via its default XavierNormal; we
use KaimingNormal fan_in which matches conv practice and the reference's
vision models reinitialize anyway).
"""

from __future__ import annotations

import math
from typing import Optional

from .. import functional as F
from .. import initializer as I
from ..layer import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose"]


def _ntuple(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, ndim, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, ndim)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        self.output_padding = output_padding
        if transpose:
            wshape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            wshape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = (in_channels // groups) * math.prod(self.kernel_size)
        default_init = None
        if not (weight_attr and getattr(weight_attr, "initializer", None)):
            std = math.sqrt(2.0 / fan_in)
            default_init = I.Normal(0.0, std)
        self.weight = self.create_parameter(wshape, attr=weight_attr,
                                            default_initializer=default_init)
        if bias_attr is False:
            self.add_parameter("bias", None)
        else:
            self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                              is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding, self.groups,
                                  self.dilation, output_size, self.data_format)
