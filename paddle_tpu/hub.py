"""paddle_tpu.hub — hubconf-driven model loading.

Parity namespace for the reference's ``paddle.hub`` (python/paddle/hub.py):
a repo directory exposes entrypoint callables in a ``hubconf.py``;
``list``/``help``/``load`` discover, document, and invoke them.

``source='local'`` is fully supported (the contract is a directory on
disk).  ``'github'``/``'gitee'`` need network access — this environment is
zero-egress, so they raise a clear error pointing at the local workflow
instead of hanging on a dead socket.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"
_CACHE: dict = {}   # abspath -> (mtime, module)


def _load_hubconf(repo_dir: str, force_reload: bool = False):
    path = os.path.abspath(os.path.join(repo_dir, _HUBCONF))
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no {_HUBCONF} in {repo_dir!r} — a hub repo directory must "
            "define its entrypoints there (reference: paddle.hub)")
    mtime = os.path.getmtime(path)
    cached = _CACHE.get(path)
    if cached is not None and cached[0] == mtime and not force_reload:
        return cached[1]   # one exec per repo (list/help/load share it)
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(path))}", path)
    mod = importlib.util.module_from_spec(spec)
    # hubconf may import siblings from its repo dir — but those imports
    # must not leak: two repos with same-named helpers.py would otherwise
    # silently share the first one's cached module
    sys.path.insert(0, repo_dir)
    before = set(sys.modules)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
        for name in set(sys.modules) - before:
            m = sys.modules[name]
            f = getattr(m, "__file__", None)
            if f and os.path.abspath(f).startswith(
                    os.path.abspath(repo_dir) + os.sep):
                del sys.modules[name]
    deps = getattr(mod, "dependencies", None)
    if deps:
        missing = [d for d in deps
                   if importlib.util.find_spec(d) is None]
        if missing:
            raise RuntimeError(
                f"hubconf at {repo_dir!r} requires missing packages: "
                f"{missing}")
    _CACHE[path] = (mtime, mod)
    return mod


def _check_source(source: str):
    if source == "local":
        return
    if source in ("github", "gitee"):
        raise RuntimeError(
            f"source={source!r} needs network access, which this "
            "environment does not have; clone the repo yourself and use "
            "source='local' with the checkout directory")
    raise ValueError(
        f"source must be 'github', 'gitee' or 'local', got {source!r}")


def _entrypoints(mod):
    return {name: fn for name, fn in vars(mod).items()
            if callable(fn) and not name.startswith("_")}


def list(repo_dir: str, source: str = "github", force_reload: bool = False):
    """Entrypoint names exported by the repo's hubconf.py.

    Reference: python/paddle/hub.py — ``list``.
    """
    _check_source(source)
    return sorted(_entrypoints(_load_hubconf(repo_dir, force_reload)))


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):
    """Docstring of one entrypoint.  Reference: hub.py — ``help``."""
    _check_source(source)
    eps = _entrypoints(_load_hubconf(repo_dir, force_reload))
    if model not in eps:
        raise ValueError(
            f"unknown entrypoint {model!r}; available: {sorted(eps)}")
    return eps[model].__doc__


def load(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Invoke one entrypoint and return its result (typically a Layer).

    Reference: hub.py — ``load``.
    """
    _check_source(source)
    eps = _entrypoints(_load_hubconf(repo_dir, force_reload))
    if model not in eps:
        raise ValueError(
            f"unknown entrypoint {model!r}; available: {sorted(eps)}")
    return eps[model](**kwargs)
