"""paddle.sparse facade — COO/CSR sparse tensors.

Reference: python/paddle/sparse/ backed by phi sparse kernels
(paddle/phi/kernels/sparse/ — part of the PHI kernel library row,
SURVEY.md §2.1).

TPU-native: sparse storage/compute delegates to jax.experimental.sparse
(BCOO/BCSR — XLA-lowered gather/scatter/dot_general).  Note the honest
perf stance: TPUs have no sparse MXU path, so XLA executes these as
gather/scatter programs — fine for sparse IO/embedding-style use, not a
CUDA-cusparse replacement; dense paddle_tpu ops remain the hot path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "is_sparse",
           "is_sparse_coo", "is_sparse_csr", "to_dense", "to_sparse_coo",
           "add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul", "mv", "relu", "tanh", "sin", "sinh", "tan",
           "asin", "asinh", "atan", "atanh", "sqrt", "square", "log1p",
           "expm1", "abs", "neg", "pow", "deg2rad", "rad2deg", "cast",
           "sum", "coalesce", "is_same_shape", "transpose", "nn"]


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient: bool = True):
    """Reference: paddle.sparse.sparse_coo_tensor(indices [ndim, nnz],
    values [nnz], shape)."""
    import numpy as _np
    import jax.core as _core
    if shape is None:
        # shape inference needs CONCRETE indices (and at least one entry);
        # under jit or with nnz=0 the caller must pass shape explicitly
        if isinstance(indices, _core.Tracer) or _np.asarray(indices).size == 0:
            raise ValueError("sparse_coo_tensor: pass `shape` explicitly "
                             "under jit or for empty tensors")
        shape = tuple(int(m) + 1 for m in _np.max(_np.asarray(indices),
                                                  axis=1))
    indices = jnp.asarray(indices)
    values = jnp.asarray(values, dtype=dtype)
    return jsparse.BCOO((values, indices.T), shape=tuple(shape))


def _tag_csr(x):
    x._paddle_csr = True
    return x


def _copy_fmt(src, dst):
    if getattr(src, "_paddle_csr", False):
        dst._paddle_csr = True
    return dst


def sparse_csr_tensor(crows, cols, values, shape,
                      dtype=None, place=None, stop_gradient: bool = True):
    """Reference: paddle.sparse.sparse_csr_tensor.  Stored as BCOO
    internally (jax's CSR support is narrower); numeric semantics
    preserved.  The CSR identity is a creation-time tag that this facade's
    own ops propagate, but pytree reconstruction (jit/grad/tree_map)
    normalizes to COO — is_sparse_csr is therefore best-effort, documented
    deviation (our single internal storage IS coordinate format)."""
    crows = jnp.asarray(crows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    values = jnp.asarray(values, dtype=dtype)
    # expand crow pointers to row indices
    counts = crows[1:] - crows[:-1]
    rows = jnp.repeat(jnp.arange(counts.shape[0], dtype=jnp.int32), counts,
                      total_repeat_length=values.shape[0])
    idx = jnp.stack([rows, cols], axis=1)
    return _tag_csr(jsparse.BCOO((values, idx), shape=tuple(shape)))


def is_sparse(x) -> bool:
    return isinstance(x, (jsparse.BCOO, jsparse.BCSR))


def is_sparse_coo(x) -> bool:
    return isinstance(x, jsparse.BCOO) and not getattr(x, "_paddle_csr",
                                                       False)


def is_sparse_csr(x) -> bool:
    return getattr(x, "_paddle_csr", False) or isinstance(x, jsparse.BCSR)


def to_dense(x):
    return x.todense() if is_sparse(x) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim: Optional[int] = None):
    return jsparse.BCOO.fromdense(jnp.asarray(x))


def _binop(op, x, y):
    xd = to_dense(x)
    yd = to_dense(y)
    out = op(xd, yd)
    if is_sparse(x) or is_sparse(y):
        res = jsparse.BCOO.fromdense(out)
        return _copy_fmt(x if is_sparse(x) else y, res)
    return out


def add(x, y, name=None):
    return _binop(jnp.add, x, y)


def subtract(x, y, name=None):
    return _binop(jnp.subtract, x, y)


def multiply(x, y, name=None):
    return _binop(jnp.multiply, x, y)


def matmul(x, y, name=None):
    """sparse @ dense -> dense (reference semantics); lowered via
    BCOO dot_general (XLA gather/scatter)."""
    if is_sparse(x):
        return x @ jnp.asarray(to_dense(y) if is_sparse(y) else y)
    return jnp.asarray(x) @ to_dense(y)


def masked_matmul(x, y, mask, name=None):
    """dense @ dense evaluated only at ``mask``'s nonzero pattern
    (reference: paddle.sparse.masked_matmul; SDDMM) — O(nnz * K) gather
    form, never materialising the dense product.  Supports the reference's
    2-D ([M,K]@[K,N], 2-col indices) and batched 3-D ([B,M,K]@[B,K,N],
    3-col indices) forms."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    idx = mask.indices
    if idx.shape[1] == 2:
        rows, cols = idx[:, 0], idx[:, 1]
        vals = jnp.sum(x[rows, :] * y[:, cols].T, axis=-1)      # [nnz]
        shape = (x.shape[0], y.shape[1])
    elif idx.shape[1] == 3:
        b_, rows, cols = idx[:, 0], idx[:, 1], idx[:, 2]
        vals = jnp.sum(x[b_, rows, :] * y[b_, :, cols], axis=-1)
        shape = (x.shape[0], x.shape[1], y.shape[2])
    else:
        raise ValueError(f"masked_matmul: {idx.shape[1]}-d mask indices "
                         f"unsupported (2-D or batched 3-D)")
    return jsparse.BCOO((vals, idx), shape=shape)


def _unary(op):
    def f(x, name=None):
        if is_sparse(x):
            return _copy_fmt(x, jsparse.BCOO((op(x.data), x.indices),
                                             shape=x.shape))
        return op(jnp.asarray(x))
    return f


relu = _unary(lambda v: jnp.maximum(v, 0))
tanh = _unary(jnp.tanh)
# the reference exposes exactly the ZERO-PRESERVING unary family on
# sparse tensors (python/paddle/sparse/unary.py) — f(0)=0, so mapping
# stored values preserves the pattern
sin = _unary(jnp.sin)
sinh = _unary(jnp.sinh)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
asinh = _unary(jnp.arcsinh)
atan = _unary(jnp.arctan)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
abs = _unary(jnp.abs)  # noqa: A001 — mirrors the reference name
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)


def pow(x, factor, name=None):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """Reference: paddle.sparse.cast — cast indices and/or values."""
    if not is_sparse(x):
        return jnp.asarray(x, value_dtype) if value_dtype else jnp.asarray(x)
    idx = x.indices if index_dtype is None else x.indices.astype(index_dtype)
    val = x.data if value_dtype is None else x.data.astype(value_dtype)
    return _copy_fmt(x, jsparse.BCOO((val, idx), shape=x.shape))


def divide(x, y, name=None):
    """Elementwise divide (dense-union semantics like the reference's
    sparse divide: entries where both are zero produce the stored
    0/0 = nan of the dense computation)."""
    return _binop(jnp.divide, x, y)


def mv(x, vec, name=None):
    """sparse [M, N] @ dense vector [N] -> dense [M]."""
    return matmul(x, jnp.asarray(vec))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Reduce-sum.  axis=None returns the dense scalar; an int axis
    returns a sparse result (jsparse bcoo_reduce_sum).  keepdim is
    unsupported on the sparse path (documented deviation)."""
    if not is_sparse(x):
        return jnp.sum(jnp.asarray(x), axis=axis, dtype=dtype,
                       keepdims=keepdim)
    if keepdim:  # both branches: the deviation is enforced, not silent
        raise ValueError("sparse sum: keepdim=True is not supported")
    if axis is None:
        return jnp.sum(x.data, dtype=dtype)
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(a % len(x.shape) for a in axes)
    out = jsparse.bcoo_reduce_sum(x, axes=axes)
    if dtype is not None:
        out = jsparse.BCOO((out.data.astype(dtype), out.indices),
                           shape=out.shape)
    return _copy_fmt(x, out)


def coalesce(x, name=None):
    """Merge duplicate coordinates (reference: paddle.sparse.coalesce)."""
    return _copy_fmt(x, x.sum_duplicates())


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def transpose(x, perm, name=None):
    if is_sparse(x):
        # O(nnz): permute the coordinate columns, no densify
        perm = tuple(perm)
        new_idx = x.indices[:, jnp.asarray(perm, jnp.int32)]
        new_shape = tuple(x.shape[p] for p in perm)
        return _copy_fmt(x, jsparse.BCOO((x.data, new_idx),
                                         shape=new_shape))
    return jnp.transpose(x, perm)


from . import nn  # noqa: E402  (paddle.sparse.nn — conv stack, sparse/nn.py)
